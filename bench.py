"""Headline benchmark: KV-cache-aware ("precise") routing vs comparators.

Reproduces the reference's capacity benchmarks (`benchmarking/37-capacity`,
`73-capacity`: precise vs estimated/load/random scheduling under
shared-prefix Poisson load) on TPU with the in-tree JAX serving engine,
per the BASELINE.json north star: *p50-TTFT reduction vs round-robin on
shared-prefix load*, plus req/s/chip and prefix-cache hit-rate.

Method — virtual-clock fleet co-simulation on one real chip:

- N "pods", each a real `Engine` (own KV page pool, block manager,
  continuous-batching scheduler) running the real Pallas paged-attention
  model; all pods share one copy of the weights (pods differ only by KV
  cache state, which is what routing exploits).
- Each pod has a virtual clock advanced by the *measured wall time* of its
  engine steps on the TPU. Pods are independent machines in a real
  deployment, so time-slicing them on one chip while accounting time
  per-pod is a faithful simulation of fleet behavior.
- KV events flow through the real write path: BlockStored/BlockRemoved →
  msgpack EventBatch → sharded KVEventsPool → shared in-memory block index
  (SURVEY §3.2). The router's read path is `KVCacheIndexer.score_tokens`
  (chunked sha256-CBOR hashing + longest-prefix scorer, SURVEY §3.1).
- Workload: G prefix groups (default 32-way), each a shared prefix of
  `PREFIX_LEN` tokens plus a unique suffix; Poisson arrivals on a 3-step
  QPS ramp (0.7x/1.0x/1.4x of the calibrated saturation rate) — the
  analogue of the reference's 3→20 QPS ramp.
- Policies (the reference's four, `37-capacity/README.md`):
  * `round_robin` — the reference's "random"/default-k8s analogue
  * `load`        — least outstanding requests
  * `estimated`   — prefix-affinity WITHOUT the index: models each pod's
    cache as a capacity-bounded LRU of routed token-block chains (with
    optional TTL decay) but never sees KV events, so it cannot know about
    real evictions, preemptions or actual cache state
  * `precise`     — KV-cache index scores (this project)

Prints ONE JSON line:
  {"metric": "p50_ttft_reduction_vs_round_robin", "value": <pct>,
   "unit": "%", "vs_baseline": <pct/50>,
   "req_s_per_chip": <precise fleet req/s per chip>,
   "prefix_cache_hit_rate": <precise prompt-token cache hit fraction>}
vs_baseline >= 1.0 means the north-star target (>=50% reduction) is met.

Env knobs (for ad-hoc runs; the driver uses defaults):
  BENCH_SMOKE=1        tiny CPU-sized run (auto when not on TPU)
  BENCH_POLICIES=a,b   subset of policies to run
  BENCH_HOST_PAGES=N   host-DRAM offload tier slots per pod (tier evidence)
  BENCH_TOTAL_PAGES=N  override per-pod HBM page-pool size
  BENCH_QPS_SCALES=x,y,z  override the ramp multipliers
  BENCH_EVENT_LAG_MS=N publish→index event visibility lag (default 2 ms —
                       the ms-scale ZMQ+decode hop of a real deployment;
                       0 restores the drain-everything optimistic co-sim)
  BENCH_EST_TTL_S=N    estimated-router affinity TTL (default off; the
                       capacity-LRU is the binding bound in these runs)
  BENCH_PRESSURE=0     skip the second (pool-pressure) pass
  BENCH_PRESSURE_PAGES=N pressure-pass pool size (default 1536 @1p4b,
                       640 @8b-int8 — past the working set, so pods evict
                       and the index's eviction awareness shows; the
                       reference's own headline regime)
  BENCH_PRESSURE_HOST_PAGES=N host-DRAM tier size for the pressure pass's
                       precise_host arm (default = the pressure pool size,
                       i.e. >=2x effective pages; 0 skips the arm). The
                       arm reruns `precise` under the SAME shrunken HBM
                       pool with the host tier + prefetch + int8 KV spill
                       on — the capacity story of ISSUE 6
  BENCH_KV_QUANT=int8  paged-KV quantization for the precise_host arm and
                       (with BENCH_HOST_PAGES) the main pass ("" = off:
                       spill full-width pages)
  BENCH_HOST_PREFETCH=1 bring-back ahead of the scheduler in host-tier
                       arms (0 = blocking allocate-time restore only)
  BENCH_HOST_TIER_POLICY=always  tier admission for host-tier arms
                       (default pins the mechanism; "auto" lets the
                       recompute-vs-restore model gate on this rig's link)
  BENCH_STALL_CAP_X=N  virtual-clock stall rejection: cap a step's wall
                       contribution at N x the pod's trailing median
                       (default 20; 0 disables). Clamped time is reported
                       per policy in the detail JSON.
  BENCH_CHUNKED_PREFILL_TOKENS=N  per-step prefill chunk budget (chunked
                       prefill + mixed prefill/decode steps; 0/unset =
                       legacy either-or scheduling) — the TTFT/ITL
                       trade-off knob
  BENCH_TRANSFER=1     cross-pod KV transfer for the precise policy: the
                       BlendedRouter runs with the transfer cost model and
                       a "pull" decision actually moves the prefix blocks
                       (source export → target import through the real
                       engine endpoints), charging the target's virtual
                       clock with the measured wall time plus modeled link
                       time; pull counts land in the detail JSON
  BENCH_TRANSFER_GBPS=N  modeled DCN link rate for the pull charge and the
                       cost model's seed transfer rate (default 10)
  BENCH_DECODE_FASTPATH=1  decode fast path on every arm's engines
                       (DECODE_FUSED_SAMPLING + DECODE_PIPELINE: device-
                       resident last tokens across steps, async D2H of
                       sampled ids) — the ISSUE 7 throughput knob
  BENCH_SPEC_DECODE=prompt_lookup  adds a `precise_spec` arm (precise
                       routing with speculative decoding) reporting an
                       acceptance-rate column
  BENCH_STEP_PHASES=1  per-arm engine step-phase decomposition
                       (schedule/prefill/decode/sample/gather/publish
                       seconds) in the detail JSON
  BENCH_DISAGG=1       disaggregated prefill/decode arm (ISSUE 9): the
                       same qps-ramp workload served by N prefill + M
                       decode pods — the TwoHopPlanner places ingest on
                       the prefill tier (warmth + measured prefill rate),
                       the chain moves over the real export/import
                       endpoints (charged wall + modeled link time), and
                       the decode tier streams tokens. Decode-tier ITL is
                       the headline: ingest never shares an engine with a
                       decode lane, so the interference chunked prefill
                       bounds is REMOVED, not amortized. Compared against
                       the same-total-pod-count mixed fleet (`precise`)
  BENCH_DISAGG_PREFILL_PODS=N  prefill-tier size (default n_pods/2,
                       min 1); decode tier gets the rest
  BENCH_REMOTE_TIER=1  remote-tier arm (ISSUE 13): re-run `precise` under
                       the pressure pool with REMOTE_TIER on — evictions
                       that would destroy the last copy of a chain demote
                       (int8 wire triple) to a simulated kvstore holder on
                       the event bus, the index learns the
                       medium="remote" entries under the HOLDER identity,
                       and the router pulls chains back (import may
                       recycle evictable pages — victims demote, so the
                       trade is lossless) instead of recomputing. Reports
                       an effective-capacity headline: fleet tokens
                       cached (all tiers + kvstore) per HBM byte
  BENCH_REMOTE_STORE_PAGES=N  kvstore holder capacity in pages (default =
                       4x the arm's per-pod pool, so the fleet working
                       set survives demotion)
  BENCH_KV_QUANT_HBM=1 quantized-HBM arm (ISSUE 16): re-run `precise`
                       under the pressure pool's HBM BYTE budget with
                       KV_QUANT_HBM=int8 — int8 pages halve bytes/page,
                       so the same bytes hold 2x the pages. The summary's
                       `kv_quant_hbm` block closes the pre-registration
                       loop (bare arm's MRC forecast at the 2x capacity
                       point vs this arm's measured hit, within 0.05) and
                       carries the tok/s/chip A/B plus the decode/sample
                       phase deltas when BENCH_STEP_PHASES=1
  BENCH_REPEATS=N      re-run the pressure arms N times and report MEDIAN
                       hit-rate fields (hit_{arm}) + the estimated/precise
                       p90 race median with spread — single noisy rounds
                       stop masquerading as signal (default 1 = legacy
                       single-shot fields). Since ISSUE 14 the median
                       treatment also covers the per-arm TTFT/ITL
                       percentile fields (p50/p90/p99 of both, with a
                       latency_spread block) and the workload-family
                       arms, so the predicted-vs-precise comparison is a
                       median, not a single draw
  BENCH_WORKLOAD_FAMILY=1  (default on) the ISSUE 14 workload-generator
                       family: four arms — `burst` (4x QPS square-wave
                       bursts over a quiet baseline), `ramp` (diurnal
                       rise-and-fall), `session` (multi-turn session
                       affinity: each session's turn k prompt extends
                       turn k-1's prefix), `swarm` (agent-swarm
                       deep-shared-prefix waves) — each run under
                       round_robin, precise, and the new `predicted`
                       policy (BlendedRouter + TTFTPredictor: routes on
                       modeled queue-wait + miss-prefill + pull cost,
                       with the audit join feeding the per-pod
                       corrector online). Acceptance: predicted p50/p99
                       TTFT <= both comparators on burst and ramp with
                       hit-rate parity vs precise (0 skips the pass)
  BENCH_TENANT_QOS=1   two-class tenant-QoS arm (ISSUE 18): a steady
                       premium trickle over a small hot-prefix set plus
                       a background tenant running the burst shape over
                       a wide churny set, on ONE capacity-constrained
                       pod. Three runs (premium alone / knob off / knob
                       on under BENCH_TENANT_QOS_SPEC) report per-tenant
                       TTFT tails, hit rates, 429s-at-the-door, priority
                       preemptions, and per-tenant MRC slices — the
                       isolation evidence for TENANT_QOS
  BENCH_TENANT_PAGES=N pool size for the tenant-QoS arm (default: the
                       premium warm set + ~6 active sequences)
  BENCH_TENANT_QOS_SPEC=...  policy for the knob-on run (default:
                       premium prio 0 weight 4; batch prio 1 with
                       max_waiting=6 and cache_share=0.3)
  BENCH_KV_INTEGRITY=1 corruption-drill arm (ISSUE 19): three runs of a
                       spill-heavy host-tier workload on ONE pod — knob
                       off (the baseline outputs), KV_INTEGRITY on clean
                       (the digest-overhead A/B), and KV_INTEGRITY on
                       with byte flips injected into spilled host pages
                       mid-run. Every flip must be detected at
                       restore/export/scrub and quarantined BEFORE any
                       token is emitted, and the drill's greedy outputs
                       must match the baseline exactly — the
                       zero-corrupted-tokens evidence; the makespan
                       ratios price the digest overhead (clean/off) and
                       the quarantine+cold-recompute recovery
                       (drill/clean)
  BENCH_KV_INTEGRITY_FLIPS=N  byte flips injected by the drill run
                       (default 4; each lands on a distinct chain)
  BENCH_KV_INTEGRITY_PAGES=N  HBM pool size for the arm (default ~2
                       active sequences, so every warm prefix lives on
                       the spill→restore edge the digests guard)
  BENCH_OBS_FED=1      fleet-federation overhead arm (ISSUE 20):
                       headline is 4-pod FleetFederator.scrape() join
                       latency (p50/p99 over 200 scrapes against fully
                       loaded in-process payloads — three tiers, SLO
                       burn, tenant slices, integrity, MRC/lifecycle/
                       audit); the A/B is engine step p50 with a ~10 Hz
                       background scraper reading LIVE engine state vs
                       the bare engine. Acceptance: step p50 ratio
                       <= 1.02x (the observation plane must not tax the
                       hot path)
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

MODEL_NAME = "bench/llama"
ALL_POLICIES = ("round_robin", "load", "estimated", "precise")
#: `predicted` (ISSUE 14) is run by the workload-family pass (and
#: BENCH_POLICIES opt-in), not the legacy main pass — the headline
#: round_robin/load/estimated/precise comparison keeps its field set.
RUNNABLE_POLICIES = ALL_POLICIES + ("predicted",)


def build_session_workload(
    rng, n_sessions, turns, prefix_len, suffix_len, vocab, qps
):
    """Multi-turn session-affinity workload (ISSUE 14 family): each
    session has a private base prefix; turn k's prompt is the first
    ``(k+1)/turns`` of it plus a unique suffix, so turn k+1 shares turn
    k's entire prefix — the pod that served the last turn holds the
    warmth, and a router that scatters a session pays full re-prefill.
    Sessions start Poisson-staggered and think between turns, so many
    sessions are in flight at once. Returns the ``build_workload``
    shape: [(arrival_time, segment=turn_idx, tokens)]."""
    out = []
    start = 0.0
    #: sessions arrive at qps/turns so total request rate ~= qps
    session_rate = max(qps / turns, 1e-9)
    for _ in range(n_sessions):
        start += float(rng.exponential(1.0 / session_rate))
        base = rng.integers(0, vocab, prefix_len).tolist()
        t = start
        for k in range(turns):
            if k:
                # Think time between turns: the session produces at
                # ~qps/n_active, keeping ~`turns` sessions concurrent.
                t += float(rng.exponential(turns / max(qps, 1e-9)))
            shared = base[: max(prefix_len * (k + 1) // turns, 1)]
            toks = shared + rng.integers(0, vocab, suffix_len).tolist()
            out.append((t, k, toks))
    out.sort(key=lambda r: r[0])
    return out


def build_swarm_workload(
    rng, n_agents, waves, prefix_len, suffix_len, vocab, qps
):
    """Agent-swarm deep-shared-prefix workload (ISSUE 14 family): every
    agent shares ONE deep system prompt; agents fire in
    near-simultaneous waves (a planner fanning out sub-agents), so the
    fleet sees a thundering herd of identical prefixes — the regime
    where warmth-first routing piles the whole wave onto one pod and
    queue time eats the cache win."""
    base = rng.integers(0, vocab, prefix_len).tolist()
    out = []
    t = 0.0
    for w in range(waves):
        t += float(rng.exponential(n_agents / max(qps, 1e-9)))
        for _ in range(n_agents):
            jitter = float(rng.exponential(0.2 / max(qps, 1e-9)))
            toks = base + rng.integers(0, vocab, suffix_len).tolist()
            out.append((t + jitter, w, toks))
    out.sort(key=lambda r: r[0])
    return out


def build_workload(
    rng, n_groups, reqs_per_group, prefix_len, suffix_len, vocab, qps_ramp
):
    """Poisson arrival schedule over shared-prefix groups, on a QPS ramp.

    ``qps_ramp`` is a list of rates; the request stream is split into
    equal consecutive segments, one per rate. Returns
    [(arrival_time, segment_idx, tokens)] plus the segment boundaries.
    """
    prefixes = [
        rng.integers(0, vocab, prefix_len).tolist() for _ in range(n_groups)
    ]
    reqs = []
    for g in range(n_groups):
        for _ in range(reqs_per_group):
            reqs.append(prefixes[g] + rng.integers(0, vocab, suffix_len).tolist())
    rng.shuffle(reqs)
    n = len(reqs)
    seg_size = -(-n // len(qps_ramp))
    t = 0.0
    out = []
    for i, toks in enumerate(reqs):
        seg = min(i // seg_size, len(qps_ramp) - 1)
        t += float(rng.exponential(1.0 / qps_ramp[seg]))
        out.append((t, seg, toks))
    return out


class LaggedEventBus:
    """Models the publish→index latency of a real deployment: an event
    batch a pod publishes at virtual time T becomes visible to the indexer
    at T + lag (the ZMQ hop + pool decode the reference's deployments eat,
    `37-capacity/README.md` numbers include it). lag=0 reproduces the
    optimistic drain-everything co-sim. Stable sort on (visible_at, stage
    order) preserves per-pod FIFO — every pod has the same lag and
    monotonically increasing stamps."""

    def __init__(self, pool, lag_s: float):
        self.pool = pool
        self.lag_s = lag_s
        self._staged: list[tuple[float, object]] = []

    def stage(self, msg, published_at: float) -> None:
        self._staged.append((published_at + self.lag_s, msg))

    def release(self, now: float) -> None:
        """Deliver every staged message visible by ``now`` and drain the
        ingestion pool, so a routing decision at ``now`` sees exactly the
        events a real indexer would have by then."""
        keep = []
        send = []
        for item in self._staged:
            (send if item[0] <= now else keep).append(item)
        if send:
            send.sort(key=lambda item: item[0])
            for _, msg in send:
                self.pool.add_task(msg)
            self.pool.drain(timeout=10.0)
        self._staged = keep

    def flush_all(self) -> None:
        self.release(float("inf"))


#: Stall rejection for the virtual clock (BENCH_STALL_CAP_X; 0 disables):
#: a step's wall-time contribution is capped at this multiple of the
#: pod's trailing-median step time (floor 1 s). The co-sim attributes
#: MEASURED step wall time to a pod's virtual clock, so a multi-minute
#: dev-tunnel wedge during one step would charge a real deployment's
#: pod with a stall no TPU-VM ever sees and poison the whole policy's
#: tail (observed: one 7-minute stall turned a 3 s pressure p90 into
#: 206 s). Clamped time is counted and reported in the detail JSON —
#: a run that needed heavy clamping is visibly flagged, not silently
#: cleaned.
STALL_CAP_X = float(os.environ.get("BENCH_STALL_CAP_X", "20"))

#: Per-arm engine step-phase decomposition (BENCH_STEP_PHASES=1): every
#: pod engine records schedule/prefill/decode/sample/gather/publish wall
#: seconds (the PR 5 telemetry), aggregated into the detail JSON — the
#: "where did the step time go" columns of the decode-fast-path record.
#: Off by default: the extra clock reads, though small, perturb measured
#: step times.
STEP_PHASES = os.environ.get("BENCH_STEP_PHASES", "0") == "1"


class Pod:
    """One simulated serving replica: a real engine + a virtual clock."""

    def __init__(self, pod_id, engine_cfg, params, publish, bus):
        from collections import deque

        from llm_d_kv_cache_manager_tpu.server.engine import Engine

        self.pod_id = pod_id
        self._make_msg = publish(pod_id)
        self.bus = bus
        self._unstamped: list[object] = []
        # Stage the raw events; step_timed builds the wire message with the
        # post-step clock as the batch's publish timestamp (events are
        # flushed at the end of engine.step()), so the staleness probes see
        # honest virtual publish times.
        self.engine = Engine(
            engine_cfg,
            params=params,
            on_events=lambda events: self._unstamped.append(list(events)),
        )
        self.engine.obs_step_timing = STEP_PHASES
        self.clock = 0.0
        self.seqs = []  # every sequence routed here
        self.hit_stats: dict[int, tuple[int, int]] = {}  # first-prefill hits
        self._first_token_seen: set[int] = set()
        #: virtual-clock first-token / finish instants, for ITL percentiles
        self.first_clock: dict[int, float] = {}
        self.finish_clock: dict[int, float] = {}
        self._step_samples = deque(maxlen=64)
        self.stall_clamped_s = 0.0
        self.stall_clamped_steps = 0

    @property
    def load(self) -> int:
        s = self.engine.scheduler
        return len(s.waiting) + len(s.running)

    def step_timed(self, ttfts, arrivals):
        t0 = time.perf_counter()
        done = self.engine.step()
        dt = time.perf_counter() - t0
        if STALL_CAP_X and len(self._step_samples) >= 20:
            med = sorted(self._step_samples)[len(self._step_samples) // 2]
            cap = max(med * STALL_CAP_X, 1.0)
            if dt > cap:
                self.stall_clamped_s += dt - cap
                self.stall_clamped_steps += 1
                dt = cap
        self._step_samples.append(dt)
        self.clock += dt
        self.flush_staged()
        # Record first-token virtual times (running lanes catch prefill
        # first-tokens; `done` catches sequences that finished this step).
        sched = self.engine.scheduler
        for seq in done:
            self.finish_clock[seq.seq_id] = self.clock
        for seq in list(sched.running) + done:
            if seq.num_generated >= 1 and seq.seq_id not in self._first_token_seen:
                self._first_token_seen.add(seq.seq_id)
                self.first_clock[seq.seq_id] = self.clock
                if seq.seq_id in arrivals:
                    ttfts[seq.seq_id] = self.clock - arrivals[seq.seq_id]
                # Snapshot cache-hit accounting at FIRST prefill: a later
                # preemption re-prefill "hits" the sequence's own surviving
                # pages (and folds generated tokens into the prompt), which
                # would overstate shared-prefix reuse under saturation.
                self.hit_stats[seq.seq_id] = (
                    seq.num_cached_prompt,
                    len(seq.prompt_tokens),
                )

    def flush_staged(self):
        # Stage any events the engine emitted outside step() (e.g. an
        # import_kv_blocks flush): a pod with no work never steps, so
        # without this the index would never learn those blocks landed.
        if self._unstamped:
            for events in self._unstamped:
                self.bus.stage(self._make_msg(events, self.clock), self.clock)
            self._unstamped.clear()

    def advance_to(self, t, ttfts, arrivals):
        while self.engine.has_work and self.clock < t:
            self.step_timed(ttfts, arrivals)

    def drain(self, ttfts, arrivals, max_steps=200_000):
        for _ in range(max_steps):
            if not self.engine.has_work:
                return
            self.step_timed(ttfts, arrivals)
        raise RuntimeError("pod failed to drain")


def make_event_pipeline(index, n_pods, staleness=None, audit=None):
    """Real write path: msgpack-encode batches, shard into the events pool.

    ``staleness``/``audit`` (optional ``obs.audit`` trackers) attach the
    ISSUE 10 probes to the same pool the product runs — the bench measures
    the audit plane itself, not a stand-in."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
        KVEventsPool,
        KVEventsPoolConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import EventBatch
    from llm_d_kv_cache_manager_tpu.kvcache.kvevents.pool import Message

    pool = KVEventsPool(
        index,
        KVEventsPoolConfig(concurrency=min(4, n_pods)),
        staleness=staleness,
        audit=audit,
    )
    pool.start()

    _seqs = {}

    def publish(pod_id):
        # Int ids name engine pods; string ids name auxiliary publishers
        # (the remote arm's kvstore holder) verbatim.
        pod_name = pod_id if isinstance(pod_id, str) else f"tpu-pod-{pod_id}"

        def make_msg(events, ts=0.0):
            # Virtual publish timestamp + per-publisher seq: the staleness
            # probes read both off the wire exactly as in production.
            batch = EventBatch(ts=ts, events=list(events))
            seq = _seqs.get(pod_name, 0)
            _seqs[pod_name] = seq + 1
            return Message(
                topic=f"kv@{pod_name}@{MODEL_NAME}",
                pod_identifier=pod_name,
                model_name=MODEL_NAME,
                payload=batch.to_payload(),
                seq=seq,
            )

        return make_msg

    return pool, publish


def _audit_summary(auditor) -> dict:
    """Fleet-level predicted-vs-realized columns from the joined audits:
    the realized hit ratio (sum realized / sum predicted over decisions
    that promised warmth) and the attributed miss mix."""
    rows = auditor.recent(limit=1_000_000)
    predicted = sum(r["predicted_blocks"] for r in rows)
    realized = sum(
        min(r["realized_blocks"], r["predicted_blocks"]) for r in rows
    )
    ratios = sorted(r["ratio"] for r in rows if r["ratio"] is not None)
    snap = auditor.snapshot()
    return {
        "joined": snap["joined"],
        "unmatched": snap["unmatched_realized"],
        "predicted_blocks": predicted,
        "realized_blocks": sum(r["realized_blocks"] for r in rows),
        # Capped per-request (a request can't realize MORE than promised
        # toward this ratio — overshoot is a different, happy story).
        "realized_over_predicted": (
            round(realized / predicted, 4) if predicted else None
        ),
        "ratio_p50": (
            ratios[len(ratios) // 2] if ratios else None
        ),
        "misses": {k: v for k, v in snap["miss_causes"].items() if v},
        # Predicted-TTFT honesty (ISSUE 14, predicted arm only): median
        # realized/predicted TTFT over the joined decisions — the
        # acceptance band is [0.8, 1.25].
        **(
            {"ttft_ratio_p50": snap["ttft_ratio_p50"]}
            if "ttft_ratio_p50" in snap
            else {}
        ),
    }


def run_policy(
    policy, workload, params, engine_cfg, n_pods, max_new_tokens,
    remote=False, mrc=False,
):
    """Run one routing policy over the workload; returns per-request and
    fleet-level metrics.

    ``remote=True`` (requires ``engine_cfg.remote_tier``) attaches the
    ISSUE 13 remote tier: every pod's last-copy evictions demote to a
    simulated kvstore holder (``tpu-kvstore-0``) whose
    ``BlockStored(medium="remote")`` events ride the same lagged bus
    under the HOLDER identity; the router's remote arm pulls demoted
    chains back through the real import endpoints (charged measured wall
    + modeled link time, demotions charged link time on the visibility
    clock only — the push itself is background work on a real pod).

    ``mrc=True`` (ISSUE 15) attaches the PRODUCT reuse-distance
    estimator (``obs/lifecycle.ReuseDistanceEstimator``, full sampling)
    to every pod's block manager and reports the miss-ratio curve's
    predicted hit rate at the arm's configured tier capacities — the
    number the pressure-arm validation compares against the measured
    ``prefix_cache_hit_rate``."""
    from llm_d_kv_cache_manager_tpu.kvcache import (
        KVCacheIndexer,
        KVCacheIndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    page = engine_cfg.block_manager.page_size
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=page))
    )
    # Routing-quality audit plane (ISSUE 10), on the PRODUCT trackers:
    # staleness (publish→index-visibility on the virtual clock) and the
    # predicted-vs-realized join are only meaningful for arms that consume
    # the index — other policies never release events at decision time, so
    # their lag would just measure the final drain.
    staleness = auditor = None
    vnow = [0.0]  # virtual "apply instant" the tracker's clock reads
    if policy in ("precise", "predicted"):
        from llm_d_kv_cache_manager_tpu.obs.audit import (
            RouteAuditor,
            StalenessTracker,
        )

        staleness = StalenessTracker(clock=lambda: vnow[0])
        auditor = RouteAuditor(
            index=indexer.kv_block_index,
            model_name=MODEL_NAME,
            ring=len(workload) + 1,
            pending_cap=len(workload) + 1,
        )
    pool, publish = make_event_pipeline(
        indexer.kv_block_index, n_pods, staleness=staleness, audit=auditor
    )
    lag_s = float(os.environ.get("BENCH_EVENT_LAG_MS", "2")) / 1000.0
    bus = LaggedEventBus(pool, lag_s)
    pods = [Pod(i, engine_cfg, params, publish, bus) for i in range(n_pods)]
    pod_names = [f"tpu-pod-{i}" for i in range(n_pods)]
    mrc_est = None
    if mrc:
        from llm_d_kv_cache_manager_tpu.obs.lifecycle import (
            ReuseDistanceEstimator,
        )

        # Full sampling + a stack deep enough that no distance in the
        # smoke working set truncates: the validation judges the MRC
        # math, not its sampling variance.
        mrc_est = [
            ReuseDistanceEstimator(sample_rate=1.0, max_tracked=1 << 15)
            for _ in pods
        ]
        for p, est in zip(pods, mrc_est):
            p.engine.block_manager.attach_lifecycle(None, est)
    blended = None
    est = aff = None
    predictor = None
    if policy in ("estimated", "precise", "predicted"):
        from llm_d_kv_cache_manager_tpu.kvcache import PrefixAffinityTracker
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            ChunkedTokenDatabase,
            TokenProcessorConfig,
        )

        ttl_env = os.environ.get("BENCH_EST_TTL_S", "")
        # The tracker IS product code (kvcache/router.py): as `estimated`
        # it is the index-free comparator; as `aff` it is precise's
        # cold-index tiebreak. Modeled capacity covers everything the pod
        # can serve hits from: HBM pages plus the host-DRAM tier when
        # enabled (otherwise the estimated baseline would be handicapped
        # in exactly the BENCH_HOST_PAGES tier-evidence runs).
        router = PrefixAffinityTracker(
            n_pods,
            capacity_blocks=engine_cfg.block_manager.total_pages
            + engine_cfg.block_manager.host_pages,
            ttl_s=float(ttl_env) if ttl_env else None,
            token_processor=ChunkedTokenDatabase(
                TokenProcessorConfig(block_size=page)
            ),
        )
        if policy == "estimated":
            est = router
        else:
            aff = router  # precise's cold-index affinity tiebreak
            from llm_d_kv_cache_manager_tpu.kvcache import BlendedRouter

            blended = BlendedRouter(
                score_fn=lambda toks, names: indexer.score_tokens(
                    toks, MODEL_NAME, names
                ),
                affinity=aff,
                loads_fn=lambda names: [
                    pods[pod_names.index(nm)].load for nm in names
                ],
                auditor=auditor,
            )
        if policy == "predicted":
            # Predicted-TTFT routing (ISSUE 14): THE PRODUCT PATH —
            # BlendedRouter with a TTFTPredictor attached routes on
            # modeled queue wait + miss-prefill (+ pull cost), signals
            # read live off the pod engines (queue depth + the online
            # prefill-rate EMA, the same carriers heartbeats ship). The
            # audit join below feeds realized TTFT back into the per-pod
            # corrector ONLINE, so the model self-corrects mid-run.
            from llm_d_kv_cache_manager_tpu.kvcache import (
                PodSignals,
                TTFTPredictor,
                TTFTPredictorConfig,
            )

            # NOTE default_concurrency stays 1: the engine's prefill-rate
            # EMA is BATCH-AGGREGATE tokens/s, so q x (tokens/rate) is
            # already amortized over the batch width — dividing again
            # would double-count the parallelism and under-weight queues.
            tie_env = os.environ.get("BENCH_PREDICT_TIE_BAND", "")
            predictor = TTFTPredictor(
                TTFTPredictorConfig(
                    block_size=page,
                    **({"tie_band": float(tie_env)} if tie_env else {}),
                )
            )
            auditor.ttft_corrector = predictor.corrector
            blended.predictor = predictor
            def _signals(names):
                out = []
                for nm in names:
                    sched = pods[pod_names.index(nm)].engine.scheduler
                    out.append(
                        PodSignals(
                            name=nm,
                            # The TTFT-relevant queue is the PREFILL
                            # backlog: this engine schedules prefill
                            # first, so decode-running lanes barely
                            # delay a new arrival's first token —
                            # counting them as full queue slots (the
                            # load tiebreak's definition) made busy-but-
                            # prefill-idle pods look slow and convoyed
                            # arrivals onto genuinely idle ones.
                            queue_depth=float(
                                len(sched.waiting)
                                + len(sched.prefilling)
                                + 0.4 * len(sched.running)
                            ),
                            prefill_rate=pods[
                                pod_names.index(nm)
                            ].engine._prefill_rate,
                        )
                    )
                return out

            blended.signals_fn = _signals

    # Cross-pod KV transfer arm (BENCH_TRANSFER=1, precise only): the
    # router runs with the transfer cost model, and a "pull" decision
    # actually moves the blocks through the real engine export/import
    # endpoints. The pull is charged end-to-end to the TARGET pod's
    # virtual clock: measured export+import wall time (the real gather/
    # scatter cost on this rig) plus wire_bytes / BENCH_TRANSFER_GBPS
    # (the DCN hop an in-process co-sim cannot measure).
    cost_model = None
    link_bytes_s = 0.0
    pull_stats = {"pulls": 0, "pulled_blocks": 0, "pull_s": 0.0}
    if blended is not None and (
        remote or os.environ.get("BENCH_TRANSFER", "0") == "1"
    ):
        from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
            TransferCostModel,
            TransferCostModelConfig,
        )

        link_bytes_s = (
            float(os.environ.get("BENCH_TRANSFER_GBPS", "10")) * 1e9 / 8
        )
        cost_model = TransferCostModel(
            TransferCostModelConfig(
                block_bytes=pods[0].engine.kv_block_bytes, block_size=page
            )
        )
        # Seed the link rate so the first pull can happen at all (the
        # EMA then blends in measured end-to-end samples); prefill rate
        # feeds from the engines' own online EMAs per arrival.
        cost_model.seed_rates(transfer_bytes_s=link_bytes_s)
        blended.cost_model = cost_model

    # Remote tier (BENCH_REMOTE_TIER=1, precise only): a simulated
    # kvstore holder backed by the PRODUCT RemoteBlockStore. Demotions
    # are wire-ready payloads the engines build on eviction (int8 triple
    # under kv_quant); acceptance publishes BlockStored(medium="remote")
    # under the HOLDER identity through the same lagged bus, so the
    # index's remote entries — and their death-of-holder eviction
    # semantics — are exactly the product path.
    kv_name = "tpu-kvstore-0"
    store = None
    remote_detail = None
    if remote:
        assert blended is not None and engine_cfg.remote_tier
        import jax.numpy as jnp

        from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
            RemoteBlockStore,
            RemoteStoreConfig,
        )
        from llm_d_kv_cache_manager_tpu.models import quant as _quant

        mc = engine_cfg.model
        shape = (mc.n_layers, page, mc.n_kv_heads, mc.hd)
        store_pages = int(
            os.environ.get(
                "BENCH_REMOTE_STORE_PAGES",
                str(engine_cfg.block_manager.total_pages * 4),
            )
        )
        kv_make_msg = publish(kv_name)
        kv_clock = [0.0]  # holder-side publish instant (set per demotion)
        store = RemoteBlockStore(
            RemoteStoreConfig(
                capacity_pages=store_pages,
                page_size=page,
                page_shape=shape,
                dtype=str(np.dtype(jnp.dtype(mc.dtype).name)),
                scale_bytes=int(np.prod(_quant.kv_scale_shape(shape))) * 4,
                init_hash=pods[0].engine.block_manager.token_db.init_hash,
            ),
            on_events=lambda events: bus.stage(
                kv_make_msg(events, kv_clock[0]), kv_clock[0]
            ),
        )
        remote_detail = {
            "store_pages": store_pages,
            "demoted_blocks": 0,
            "demote_wire_bytes": 0,
            "remote_pulls": 0,
            "remote_pulled_blocks": 0,
        }

        def demotion_sink(pod):
            def sink(payloads):
                wire = sum(b.wire_bytes for b in payloads)
                remote_detail["demoted_blocks"] += len(payloads)
                remote_detail["demote_wire_bytes"] += wire
                # The push is background work on a real pod; only the
                # event-visibility clock pays the link time.
                kv_clock[0] = pod.clock + (
                    wire / link_bytes_s if link_bytes_s else 0.0
                )
                store.accept(payloads)

            return sink

        for pod in pods:
            pod.engine.on_demotion = demotion_sink(pod)
        # Remote read path: the index's score for the holder alone — the
        # router pulls only when the measured cost model says the move
        # beats both the warm local option and recompute. placement=
        # "pull_source" is the product pattern: a FleetHealth-wired
        # scorer must not blank kvstore holders out of THIS query (the
        # serving filter rightly would).
        blended.remote_score_fn = lambda toks: {
            p: s
            for p, s in indexer.score_tokens(
                toks, MODEL_NAME, [kv_name], placement="pull_source"
            ).items()
            if s > 0
        }

    ttfts: dict[int, float] = {}
    arrivals: dict[int, float] = {}
    segments: dict[int, int] = {}
    rid_of: dict[int, str] = {}  # seq_id -> audit request id (precise)
    joined: set[int] = set()

    def join_realized():
        """Join every first-tokened request's ground truth (realized
        cache hits + realized TTFT on the virtual clock) against its
        recorded decision. The predicted arm calls this ONLINE per
        arrival so the corrector learns mid-run (the audit plane as an
        actuator); every audited arm calls it once more at drain so the
        end-of-run columns cover the full workload."""
        for i, pod in enumerate(pods):
            for sid in list(pod.first_clock):
                if sid in joined or sid not in pod.hit_stats:
                    continue
                rid = rid_of.get(sid)
                if rid is None:
                    continue
                joined.add(sid)
                cached, _ = pod.hit_stats[sid]
                auditor.record_realized(
                    rid,
                    pod_names[i],
                    cached // page,
                    realized_ttft_s=ttfts.get(sid),
                )

    rr = 0
    for req_i, (t, seg, tokens) in enumerate(workload):
        # Advance every pod to the arrival instant so the index reflects
        # fleet state at routing time, then drain in-flight events.
        for pod in pods:
            pod.advance_to(t, ttfts, arrivals)
        if policy == "predicted":
            join_realized()  # online corrector feedback
        if policy in ("precise", "predicted"):
            # Events released now APPLY now on the virtual clock — the
            # staleness tracker's "index visibility" instant.
            vnow[0] = t
            # The index sees exactly the events a real deployment's
            # indexer would have by the arrival instant (publish + lag);
            # routing is THE PRODUCT PATH (kvcache/router.BlendedRouter:
            # index score → routed-affinity tiebreak → load — the blend
            # that fixed the measured cold-index scatter under thrash,
            # results/routing_capacity.md round 4).
            bus.release(t)
            if cost_model is not None:
                rates = [
                    p.engine._prefill_rate
                    for p in pods
                    if p.engine._prefill_rate
                ]
                if rates:
                    cost_model.seed_rates(
                        prefill_tokens_s=float(np.median(rates))
                    )
            decision = blended.route(
                tokens, pod_names, now=t, request_id=f"req-{req_i}"
            )
            best = pod_names.index(decision.pod)
            if decision.action == "pull" and decision.pull_source is not None:
                tgt = pods[best]
                hashes = indexer.token_processor.prefix_hashes(tokens)
                t0 = time.perf_counter()
                if store is not None and decision.pull_source == kv_name:
                    # Bring-back from the kvstore holder: wire-ready
                    # payloads, no source engine work.
                    blocks = store.serve(hashes)
                else:
                    src = pods[pod_names.index(decision.pull_source)]
                    blocks = src.engine.export_kv_blocks(hashes)
                n_imp = tgt.engine.import_kv_blocks(blocks)
                wall = time.perf_counter() - t0
                wire = sum(b.wire_bytes for b in blocks)
                link_s = wire / link_bytes_s if wire and link_bytes_s else 0.0
                tgt.clock = max(tgt.clock, t) + wall + link_s
                if wire:
                    cost_model.observe_transfer(wire, wall + link_s)
                pull_stats["pulls"] += 1
                pull_stats["pulled_blocks"] += n_imp
                pull_stats["pull_s"] += wall + link_s
                if store is not None and decision.pull_source == kv_name:
                    remote_detail["remote_pulls"] += 1
                    remote_detail["remote_pulled_blocks"] += n_imp
        elif policy == "estimated":
            keys = est.keys(tokens)
            best = max(
                range(n_pods),
                key=lambda i: (est.score(keys, i, t), -pods[i].load, -i),
            )
            est.record(keys, best, t)
        elif policy == "load":
            best = min(range(n_pods), key=lambda i: (pods[i].load, i))
        else:  # round_robin
            best = rr % n_pods
            rr += 1
        pod = pods[best]
        if not pod.engine.has_work:
            pod.clock = max(pod.clock, t)
        seq = pod.engine.add_request(
            tokens, SamplingParams(max_new_tokens=max_new_tokens)
        )
        pod.seqs.append(seq)
        arrivals[seq.seq_id] = t
        segments[seq.seq_id] = seg
        if auditor is not None:
            rid_of[seq.seq_id] = f"req-{req_i}"
    for pod in pods:
        pod.drain(ttfts, arrivals)
    if staleness is not None:
        # Leftover events apply at the end of the run on the virtual clock.
        vnow[0] = max(p.clock for p in pods)
    bus.flush_all()
    pool.drain(timeout=10.0)
    if auditor is not None:
        # Join the pods' ground truth (first-prefill cache hits + virtual
        # TTFT, the same accounting the headlines use) against every
        # recorded decision — the predicted-vs-realized / miss-attribution
        # columns. The predicted arm already joined most online; this
        # sweeps the tail.
        join_realized()
    pool.shutdown()
    indexer.shutdown()

    n_req = len(workload)
    assert len(ttfts) == n_req, f"lost requests: {len(ttfts)}/{n_req}"
    all_ttfts = np.asarray(list(ttfts.values()))
    n_segments = max(segments.values()) + 1
    per_seg = [
        np.asarray([ttfts[sid] for sid, s in segments.items() if s == seg])
        for seg in range(n_segments)
    ]

    # Fleet accounting. Makespan = the slowest pod's busy clock: the
    # virtual duration of the whole run. Each pod is one chip here.
    makespan = max(p.clock for p in pods)
    prompt_tokens = sum(n for p in pods for _, n in p.hit_stats.values())
    cached_tokens = sum(c for p in pods for c, _ in p.hit_stats.values())
    out_tokens = sum(len(s.output_tokens) for p in pods for s in p.seqs)
    stall_clamped_s = sum(p.stall_clamped_s for p in pods)
    stall_clamped_steps = sum(p.stall_clamped_steps for p in pods)
    # Per-request mean ITL on the virtual clock: (finish - first token) /
    # (generated - 1). The serving-SLO companion to TTFT — decode-lane
    # interference (chunked prefill, batching width) shows here first.
    itls = np.asarray(
        [
            (p.finish_clock[s.seq_id] - p.first_clock[s.seq_id])
            / (s.num_generated - 1)
            for p in pods
            for s in p.seqs
            if s.num_generated > 1
            and s.seq_id in p.first_clock
            and s.seq_id in p.finish_clock
        ]
    )
    # Host-DRAM tier evidence (host-tier arms): fleet-aggregated spill/
    # restore/prefetch counters, so the detail JSON shows the tier WORKING
    # (a hit-rate win with zero restores would mean the pool was simply
    # never pressured).
    host_detail = None
    if engine_cfg.block_manager.host_pages > 0:
        host_detail = {}
        for p in pods:
            for key, val in p.engine.block_manager.host_stats.items():
                host_detail[key] = host_detail.get(key, 0) + val
            for key, val in p.engine.host_prefetch_stats.items():
                key = f"prefetch_{key}"
                host_detail[key] = host_detail.get(key, 0) + val
    # Speculative-decode evidence (spec arms): fleet-aggregated proposal/
    # acceptance counters — the acceptance-rate column of the record.
    spec_detail = None
    if engine_cfg.spec_decode != "off":
        spec_detail = {"proposed": 0, "accepted": 0, "verify_steps": 0, "bursts": 0}
        for p in pods:
            for key in spec_detail:
                spec_detail[key] += p.engine.spec_stats[key]
        spec_detail["acceptance_rate"] = (
            round(spec_detail["accepted"] / spec_detail["proposed"], 4)
            if spec_detail["proposed"]
            else None
        )
    # Step-phase decomposition (BENCH_STEP_PHASES=1): fleet-summed engine
    # phase seconds, so each arm's record shows where step time went
    # (sample ~ 0 when the fused fast path overlaps the device_get).
    phase_detail = None
    if STEP_PHASES:
        phase_detail = {}
        for p in pods:
            for key, val in p.engine.step_stats.items():
                phase_detail[key] = round(phase_detail.get(key, 0) + val, 4)
    # Routing-quality columns (ISSUE 10): event-plane staleness
    # percentiles on the virtual clock, and the predicted-vs-realized
    # audit join with miss attribution — the ground truth ROADMAP items
    # 3 and 4 will be judged against.
    staleness_detail = None
    if staleness is not None:
        pct = staleness.percentiles()
        snap = staleness.snapshot()
        staleness_detail = {
            "events": snap["events_observed"],
            "p50_ms": (
                round(pct["p50"] * 1000, 3) if pct["p50"] is not None else None
            ),
            "p99_ms": (
                round(pct["p99"] * 1000, 3) if pct["p99"] is not None else None
            ),
            "max_ms": round(snap["max_lag_s"] * 1000, 3),
        }
    audit_detail = _audit_summary(auditor) if auditor is not None else None
    if remote_detail is not None:
        # Effective-capacity headline (ISSUE 13): tokens the fleet holds
        # cached across EVERY tier (HBM + host + kvstore) per HBM byte it
        # actually paid for — the number a single-pod tier cannot reach.
        import jax.numpy as jnp

        mc = engine_cfg.model
        page_bytes = (
            2
            * mc.n_layers
            * page
            * mc.n_kv_heads
            * mc.hd
            * np.dtype(jnp.dtype(mc.dtype).name).itemsize
        )
        fleet_pages = (
            sum(
                p.engine.block_manager.num_cached_pages
                + p.engine.block_manager.num_host_cached_pages
                for p in pods
            )
            + len(store)
        )
        hbm_pages = n_pods * (engine_cfg.block_manager.total_pages - 1)
        remote_detail.update(
            {
                "store_cached": len(store),
                "store_stats": dict(store.stats),
                "fleet_cached_tokens": fleet_pages * page,
                "hbm_pages": hbm_pages,
                "hbm_bytes": hbm_pages * page_bytes,
                "effective_capacity_x_hbm": (
                    round(fleet_pages / hbm_pages, 4) if hbm_pages else None
                ),
                "tokens_per_hbm_gib": (
                    round(
                        fleet_pages * page / (hbm_pages * page_bytes / 2**30),
                        1,
                    )
                    if hbm_pages
                    else None
                ),
            }
        )
    # Reuse-distance MRC columns (ISSUE 15): the fleet-weighted predicted
    # hit rate at each tier's cumulative capacity (per-pod curves weighted
    # by sampled accesses — each pod's curve only speaks for the stream it
    # saw). "hbm_fleet_share" models the remote tier as extra per-pod LRU
    # capacity: HBM plus this pod's share of the shared store.
    mrc_detail = None
    if mrc_est is not None:
        total_cap = engine_cfg.block_manager.total_pages - 1
        caps = {"hbm": total_cap}
        # KV_QUANT_HBM sizing point (ISSUE 16): int8 HBM pages halve the
        # bytes per page, so the same HBM byte budget holds 2x the pages
        # (minus the reserved page 0). Read on the UNQUANTIZED arm, this
        # is the pre-registered forecast the quantized arm must then
        # measure within 0.05 — the "2x point" of the MRC sizing runbook.
        caps["hbm_2x"] = 2 * engine_cfg.block_manager.total_pages - 1
        if engine_cfg.block_manager.host_pages > 0:
            caps["hbm_host"] = total_cap + engine_cfg.block_manager.host_pages
        if remote and store is not None:
            caps["hbm_fleet_share"] = (
                total_cap + store.config.capacity_pages // n_pods
            )

        def fleet_hit(cap):
            num, den = 0.0, 0
            for est in mrc_est:
                h = est.predicted_hit_rate(cap)
                if h is not None:
                    num += h * est.sampled
                    den += est.sampled
            return round(num / den, 4) if den else None

        sampled = sum(est.sampled for est in mrc_est)
        cold = sum(est.cold for est in mrc_est)
        mrc_detail = {
            "accesses": sum(est.accesses for est in mrc_est),
            "sampled": sampled,
            "cold_fraction": round(cold / sampled, 4) if sampled else None,
            "capacities": caps,
            "predicted_hit": {name: fleet_hit(c) for name, c in caps.items()},
        }
    # The Pod.on_events closure references the Pod (staging buffer), so
    # Pod <-> Engine is now a reference CYCLE: without an explicit collect,
    # each policy's engines (~GBs of donated KV pools on the chip) survive
    # into the next policy until the cycle collector happens to run — which
    # OOMs the second policy on a 16 GB chip.
    pods.clear()
    gc.collect()
    return {
        "p50_ttft_s": float(np.median(all_ttfts)),
        "p90_ttft_s": float(np.percentile(all_ttfts, 90)),
        "p99_ttft_s": float(np.percentile(all_ttfts, 99)),
        "mean_ttft_s": float(np.mean(all_ttfts)),
        "p50_itl_s": float(np.median(itls)) if itls.size else None,
        "p90_itl_s": float(np.percentile(itls, 90)) if itls.size else None,
        "p99_itl_s": float(np.percentile(itls, 99)) if itls.size else None,
        "mean_itl_s": float(np.mean(itls)) if itls.size else None,
        "p50_ttft_per_qps_segment_s": [float(np.median(s)) for s in per_seg],
        "req_s_per_chip": float(n_req / makespan / n_pods) if makespan else 0.0,
        "output_tok_s_per_chip": (
            float(out_tokens / makespan / n_pods) if makespan else 0.0
        ),
        "prefix_cache_hit_rate": (
            float(cached_tokens / prompt_tokens) if prompt_tokens else 0.0
        ),
        "makespan_s": float(makespan),
        # Tunnel-stall rejection accounting (see STALL_CAP_X): nonzero
        # means wall-time wedges were clamped out of the virtual clocks.
        "stall_clamped_s": round(stall_clamped_s, 3),
        "stall_clamped_steps": stall_clamped_steps,
        # Cross-pod pull accounting (BENCH_TRANSFER=1, precise only).
        **(
            {"transfer": {**pull_stats, "pull_s": round(pull_stats["pull_s"], 3)}}
            if cost_model is not None
            else {}
        ),
        **({"host": host_detail} if host_detail is not None else {}),
        **({"remote": remote_detail} if remote_detail is not None else {}),
        **({"mrc": mrc_detail} if mrc_detail is not None else {}),
        **({"spec": spec_detail} if spec_detail is not None else {}),
        **({"phases": phase_detail} if phase_detail is not None else {}),
        **({"staleness": staleness_detail} if staleness_detail is not None else {}),
        **({"audit": audit_detail} if audit_detail is not None else {}),
    }


def run_fleet_arm(
    workload, params, engine_cfg, max_pods, max_new_tokens, dynamic,
    start_pods=None, roomy_pool=False,
):
    """ISSUE 17 controller arm: the same co-sim engines with POD COUNT in
    the loop, under the PRODUCT ``FleetController`` (the real decision
    logic — burn x MRC-headroom with hysteresis — driven by a co-sim
    adapter whose migrate/revive actions move KV through the real engine
    export/import endpoints). ``dynamic=False`` is the comparator: the
    identical fleet pinned at ``max_pods`` for the whole run (the static
    peak fleet a capacity planner would provision for the burst top).

    The judged pair: the dynamic arm must hold TTFT percentiles through
    the bursts at FEWER pod-seconds than the static peak (pod-seconds =
    virtual provisioned time summed over pods, the bill a fleet actually
    pays). Engines run with a pool small enough that one pod cannot hold
    the workload's prefix working set but the full fleet can — the
    capacity regime where the MRC gate has something to say; burn alone
    (a compute-bound queue spike with a flat curve) correctly holds with
    ``burning_mrc_flat``.

    Scale-down live-migrates the victim's in-flight sequences through
    the product freeze/export/import/fold path; first-token times and
    first-prefill hit accounting stay with the sequence across the move
    (TTFT is a property of the REQUEST, not of whichever pod finished
    it).

    ``start_pods`` overrides the dynamic arm's initial fleet width (the
    scale-DOWN drill starts at max_pods, over-provisioned);
    ``roomy_pool`` sizes the pool so ONE pod holds the whole working
    set — the flat-MRC regime where ``idle_mrc_flat`` scale-down is the
    CORRECT call (the family default is the opposite: capacity-starved,
    where the MRC gate rightly refuses to shed warmth)."""
    import dataclasses as _dc

    from llm_d_kv_cache_manager_tpu.kvcache import (
        KVCacheIndexer,
        KVCacheIndexerConfig,
        PrefixAffinityTracker,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.controller import (
        FleetController,
        FleetControllerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.controller import (
        PodSignals as FleetPodSignals,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
        ChunkedTokenDatabase,
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.obs.lifecycle import (
        ReuseDistanceEstimator,
        debug_mrc_payload,
    )
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    page = engine_cfg.block_manager.page_size
    # Pool sizing: the fleet at max_pods holds the whole prefix working
    # set with slack; one pod holds only a fraction of it. BENCH_FLEET_
    # PAGES overrides.
    prompt_pages = max(
        -(-(len(toks) + max_new_tokens + 1) // page) for _, _, toks in workload
    )
    distinct = len({tuple(toks[: page * 2]) for _, _, toks in workload})
    working = max(distinct, 2) * prompt_pages
    if roomy_pool:
        fleet_pages = working + prompt_pages + 1
    else:
        fleet_pages = int(
            os.environ.get(
                "BENCH_FLEET_PAGES",
                str(max(-(-working * 2 // max_pods), prompt_pages + 3) + 1),
            )
        )
    # The drill runs a longer decode tail than the family regime; widen
    # the model length (and its page buckets) when the prompt + tail
    # would not fit the family shape.
    need_len = (
        max(len(toks) for _, _, toks in workload) + max_new_tokens + page
    )
    mml = max(engine_cfg.max_model_len, need_len)
    cfg = _dc.replace(
        engine_cfg,
        max_model_len=mml,
        prefill_ctx_bucket=-(-mml // page),
        decode_pages_bucket=-(-mml // page),
        block_manager=_dc.replace(
            engine_cfg.block_manager, total_pages=fleet_pages
        ),
    )
    # The shrunken pool is a NEW kv-pool shape: compile it on a scratch
    # engine (main()'s warmup covered the full-size pool only) so neither
    # arm's virtual clocks eat the XLA compiles — the first arm to run
    # would otherwise be charged seconds of compile as fake queueing.
    longest = max((toks for _, _, toks in workload), key=len)
    warmup(
        params, cfg, max(len(longest) - 8, page), 8,
        engine_cfg.model.vocab_size, max_new_tokens,
    )
    # Unloaded cold service time, measured on a compiled scratch engine:
    # the TTFT objective self-grounds at 2x this (an SLO an operator
    # would set from a capability probe, NOT from loaded samples — a
    # threshold calibrated during a pile-up learns to call the pile-up
    # normal).
    from llm_d_kv_cache_manager_tpu.server.engine import Engine as _Engine

    probe = _Engine(cfg, params=params)
    probe.add_request(
        list(longest), SamplingParams(max_new_tokens=max_new_tokens)
    )
    t0 = time.perf_counter()
    probe.run_until_complete()
    t_cold = time.perf_counter() - t0
    del probe
    gc.collect()
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=page))
    )
    pool, publish = make_event_pipeline(indexer.kv_block_index, max_pods)
    lag_s = float(os.environ.get("BENCH_EVENT_LAG_MS", "2")) / 1000.0
    bus = LaggedEventBus(pool, lag_s)
    pods = [Pod(i, cfg, params, publish, bus) for i in range(max_pods)]
    pod_cap = fleet_pages - 1
    mrc_est = [
        ReuseDistanceEstimator(sample_rate=1.0, max_tracked=1 << 15)
        for _ in pods
    ]
    for p, est in zip(pods, mrc_est):
        p.engine.block_manager.attach_lifecycle(None, est)
    aff = PrefixAffinityTracker(
        max_pods,
        capacity_blocks=pod_cap,
        token_processor=ChunkedTokenDatabase(TokenProcessorConfig(block_size=page)),
    )
    link_bytes_s = float(os.environ.get("BENCH_TRANSFER_GBPS", "10")) * 1e9 / 8

    # THE PRODUCT ROUTER over the active subset, WITH the transfer cost
    # model. The pull arm matters more here than in the pinned-width
    # arms: score-max pins each prefix group on the one pod that is warm
    # for it (load only breaks score ties), so after a scale-up the old
    # pod would keep thrashing its pool on every group it seeded while
    # the new pods idle — the cost model is what MOVES warmth to where
    # the headroom is. BlendedRouter ranks candidates positionally; the
    # shim maps positions back to global pod slots as the active set
    # changes per arrival.
    from llm_d_kv_cache_manager_tpu.kvcache import BlendedRouter
    from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
        TransferCostModel,
        TransferCostModelConfig,
    )

    class _ActiveAff:
        order: list = []

        @staticmethod
        def keys(tokens):
            return aff.keys(tokens)

        @staticmethod
        def score(keys, i, now):
            return aff.score(keys, _ActiveAff.order[i], now)

        @staticmethod
        def record(keys, i, now):
            aff.record(keys, _ActiveAff.order[i], now)

    cost_model = TransferCostModel(
        TransferCostModelConfig(
            block_bytes=pods[0].engine.kv_block_bytes, block_size=page
        )
    )
    cost_model.seed_rates(transfer_bytes_s=link_bytes_s)
    blended = BlendedRouter(
        score_fn=lambda toks, names: indexer.score_tokens(
            toks, MODEL_NAME, names
        ),
        affinity=_ActiveAff,
        loads_fn=lambda names: [
            pods[int(nm.rsplit("-", 1)[1])].load for nm in names
        ],
        cost_model=cost_model,
    )
    pull_stats = {"pulls": 0, "pulled_blocks": 0, "pull_s": 0.0}

    ttfts: dict[int, float] = {}
    arrivals: dict[int, float] = {}
    segments: dict[int, int] = {}
    vnow = [0.0]
    n0 = (
        max_pods
        if not dynamic
        else (start_pods if start_pods is not None else 1)
    )
    active: set[int] = set(range(n0))
    retired: set[int] = set()
    span_start = {i: 0.0 for i in active}
    pod_seconds = [0.0]
    live: dict[str, tuple[int, object]] = {}  # request_id -> (pod idx, seq)
    actions: list[dict] = []
    peak_pods = [len(active)]
    migrations = {"migrated": 0, "migrated_blocks": 0, "revived_blocks": 0}
    # Measured wall time of the migration path (freeze/export/import +
    # modeled link), summed over migrations: the acceptance comparison
    # against the 30 s DRAIN_TIMEOUT_S a drain-based removal pays.
    migrate_wall = [0.0]

    # SLO-burn signal on the virtual clock: objective "TTFT <= T at p90"
    # where T self-calibrates to 2x the median of the first completions
    # (the co-sim has no absolute latency scale across rigs); burn =
    # windowed miss fraction / the 10% error budget — the same burn-rate
    # definition obs/slo.py exports as kvcache_slo_burn_rate.
    span_t = workload[-1][0] if workload else 1.0
    rec_interval = max(span_t / 60.0, 1e-3)
    burn_window = 8 * rec_interval
    samples: list[tuple[float, float]] = []  # (first-token instant, ttft)
    seen_first: set[int] = set()
    slo_t = float(
        os.environ.get("BENCH_FLEET_SLO_TTFT_S", "") or 2.0 * t_cold
    )

    def harvest():
        for p in pods:
            for sid, ft in p.first_clock.items():
                if sid in seen_first or sid not in ttfts:
                    continue
                seen_first.add(sid)
                samples.append((ft, ttfts[sid]))

    def burn_rates_now():
        recent = [v for ft, v in samples if ft >= vnow[0] - burn_window]
        # Overdue-in-queue requests count as misses NOW: a saturated pod
        # delays its own first tokens, so a burn signal built only from
        # REALIZED TTFTs goes quiet exactly when the fleet is drowning —
        # the alarm must fire while the queue is growing, not after it
        # drains.
        overdue = sum(
            1
            for sid, at in arrivals.items()
            if sid not in seen_first and vnow[0] - at > slo_t
        )
        if not recent and not overdue:
            return None
        miss = (sum(1 for v in recent if v > slo_t) + overdue) / (
            len(recent) + overdue
        )
        return {"ttft_bench_p0.9": {"w": miss / 0.1}}

    class CosimFleet:
        """FleetAdapter over the co-sim pods (indices name endpoints)."""

        def observe(self):
            burn = burn_rates_now()
            out = []
            for i in sorted(active):
                out.append(
                    FleetPodSignals(
                        pod_id=f"tpu-pod-{i}",
                        transfer_endpoint=str(i),
                        capacity_blocks=pod_cap,
                        burn_rates=burn,
                        mrc=debug_mrc_payload(mrc_est[i])[1],
                        live_requests=[
                            rid
                            for rid, (pi, s) in live.items()
                            if pi == i and not s.is_finished()
                        ],
                    )
                )
            return out

        def add_pod(self):
            idx = next(
                (
                    i
                    for i in range(max_pods)
                    if i not in active and i not in retired
                ),
                None,
            )
            if idx is None:
                return None
            active.add(idx)
            peak_pods[0] = max(peak_pods[0], len(active))
            span_start[idx] = vnow[0]
            pods[idx].clock = max(pods[idx].clock, vnow[0])
            return FleetPodSignals(
                pod_id=f"tpu-pod-{idx}",
                transfer_endpoint=str(idx),
                capacity_blocks=pod_cap,
            )

        def migrate(self, pod_id, request_id, target_endpoint):
            src = pods[int(pod_id.rsplit("-", 1)[1])]
            tgt = pods[int(target_endpoint)]
            frozen = src.engine.freeze_for_migration(request_id)
            if frozen is None:
                return False
            seq, hashes = frozen
            t0 = time.perf_counter()
            blocks = src.engine.export_kv_blocks(hashes)
            n_imp = tgt.engine.import_kv_blocks(blocks)
            wall = time.perf_counter() - t0
            wire = sum(b.wire_bytes for b in blocks)
            link_s = wire / link_bytes_s if link_bytes_s else 0.0
            tgt.clock = max(tgt.clock, vnow[0]) + wall + link_s
            migrate_wall[0] += wall + link_s
            cont = tgt.engine.add_request(
                list(seq.prompt_tokens),
                SamplingParams(max_new_tokens=seq.sampling.max_new_tokens),
                request_id=request_id,
            )
            cont.user_prompt_len = seq.user_prompt_len
            cont.num_generated = seq.num_generated
            src.engine.finish_migrated(seq)
            src.flush_staged()
            tgt.flush_staged()
            old, new = seq.seq_id, cont.seq_id
            for d in (arrivals, segments):
                if old in d:
                    d[new] = d.pop(old)
            if old in ttfts:
                # First token already served at the source: the TTFT (and
                # the first-prefill hit snapshot) is settled history — the
                # continuation must not re-record either, and the burn
                # signal's overdue scan must not see a served request as
                # still queued under its new seq_id.
                ttfts[new] = ttfts.pop(old)
                tgt._first_token_seen.add(new)
                seen_first.add(new)
                if old in src.hit_stats:
                    tgt.hit_stats[new] = src.hit_stats[old]
            tgt.seqs.append(cont)
            live[request_id] = (int(target_endpoint), cont)
            migrations["migrated"] += 1
            migrations["migrated_blocks"] += n_imp
            return True

        def retire(self, pod_id):
            idx = int(pod_id.rsplit("-", 1)[1])
            active.discard(idx)
            retired.add(idx)
            # Migration fallbacks (none expected) finish locally before
            # the pod is deprovisioned; the straggler time is billed.
            pods[idx].drain(ttfts, arrivals)
            end = max(vnow[0], pods[idx].clock)
            pod_seconds[0] += end - span_start.pop(idx)

        def warm_sets(self, limit):
            rows = []
            for i in sorted(active):
                for chain in pods[i].engine.block_manager.hot_chains(limit):
                    rows.append((str(i), chain))
            rows.sort(key=lambda r: len(r[1]), reverse=True)
            return rows[:limit]

        def revive(self, pod_id, source_endpoint, chain_hashes):
            tgt = pods[int(pod_id.rsplit("-", 1)[1])]
            src = pods[int(source_endpoint)]
            t0 = time.perf_counter()
            blocks = src.engine.export_kv_blocks(chain_hashes)
            n_imp = tgt.engine.import_kv_blocks(blocks)
            wall = time.perf_counter() - t0
            wire = sum(b.wire_bytes for b in blocks)
            tgt.clock = max(tgt.clock, vnow[0]) + wall + (
                wire / link_bytes_s if link_bytes_s else 0.0
            )
            # The revived pod has no work yet, so it will not step: stage
            # the import's BlockStored events now or the index never sees
            # the revival and routing never warms to the new pod.
            tgt.flush_staged()
            migrations["revived_blocks"] += n_imp
            return n_imp

    ctl = None
    if dynamic:
        ctl = FleetController(
            FleetControllerConfig(
                enabled=True,
                reconcile_interval_s=rec_interval,
                burn_threshold=float(
                    os.environ.get("BENCH_FLEET_BURN", "") or "1.5"
                ),
                mrc_headroom=float(
                    os.environ.get("BENCH_FLEET_HEADROOM", "") or "0.01"
                ),
                hysteresis_s=2 * rec_interval,
                min_pods=1,
                max_pods=max_pods,
            ),
            CosimFleet(),
            clock=lambda: vnow[0],
        )

    next_rec = rec_interval
    for req_i, (t, seg, tokens) in enumerate(workload):
        if ctl is not None:
            while next_rec <= t:
                for i in sorted(active):
                    pods[i].advance_to(next_rec, ttfts, arrivals)
                vnow[0] = next_rec
                harvest()
                d = ctl.reconcile()
                if d.action != "hold":
                    actions.append({"t": round(next_rec, 3), **d.as_attrs()})
                next_rec += rec_interval
        for i in sorted(active):
            pods[i].advance_to(t, ttfts, arrivals)
        vnow[0] = t
        # Release in-flight events so the index reflects fleet state at
        # the arrival instant — including the BlockStored batch from a
        # warm-set revival, which is what makes a freshly added pod
        # attract its share of the working set (the index SEES the
        # revived chains). Routing and the pull arm mirror run_policy's
        # precise+transfer path over the active subset.
        bus.release(t)
        order = sorted(active)
        names = [f"tpu-pod-{i}" for i in order]
        _ActiveAff.order = order
        rates = [
            pods[i].engine._prefill_rate
            for i in order
            if pods[i].engine._prefill_rate
        ]
        if rates:
            cost_model.seed_rates(prefill_tokens_s=float(np.median(rates)))
        decision = blended.route(tokens, names, now=t, request_id=f"r{req_i}")
        best = int(decision.pod.rsplit("-", 1)[1])
        if decision.action == "pull" and decision.pull_source is not None:
            tgt = pods[best]
            src = pods[int(decision.pull_source.rsplit("-", 1)[1])]
            hashes = indexer.token_processor.prefix_hashes(tokens)
            t0p = time.perf_counter()
            blocks = src.engine.export_kv_blocks(hashes)
            n_imp = tgt.engine.import_kv_blocks(blocks)
            wallp = time.perf_counter() - t0p
            wire = sum(b.wire_bytes for b in blocks)
            link_s = wire / link_bytes_s if wire and link_bytes_s else 0.0
            tgt.clock = max(tgt.clock, t) + wallp + link_s
            if wire:
                cost_model.observe_transfer(wire, wallp + link_s)
            tgt.flush_staged()
            pull_stats["pulls"] += 1
            pull_stats["pulled_blocks"] += n_imp
            pull_stats["pull_s"] += wallp + link_s
        pod = pods[best]
        if not pod.engine.has_work:
            pod.clock = max(pod.clock, t)
        seq = pod.engine.add_request(
            tokens,
            SamplingParams(max_new_tokens=max_new_tokens),
            request_id=f"r{req_i}",
        )
        pod.seqs.append(seq)
        arrivals[seq.seq_id] = t
        segments[seq.seq_id] = seg
        live[f"r{req_i}"] = (best, seq)
    if ctl is not None:
        # Keep reconciling through the decode tail: arrivals stopped, the
        # burn signal goes calm, the curve flattens — the controller
        # scales the fleet back down, LIVE-MIGRATING in-flight decodes to
        # survivors (the scale-down path the pod-seconds bill rewards).
        for _ in range(100_000):
            if not any(pods[i].engine.has_work for i in active):
                break
            for i in sorted(active):
                pods[i].advance_to(next_rec, ttfts, arrivals)
            vnow[0] = max(next_rec, vnow[0])
            harvest()
            d = ctl.reconcile()
            if d.action != "hold":
                actions.append({"t": round(next_rec, 3), **d.as_attrs()})
            next_rec += rec_interval
        else:
            raise RuntimeError("fleet arm failed to drain")
    for i in sorted(active):
        pods[i].drain(ttfts, arrivals)
    bus.flush_all()
    pool.drain(timeout=10.0)
    pool.shutdown()
    indexer.shutdown()

    n_req = len(workload)
    assert len(ttfts) == n_req, f"lost requests: {len(ttfts)}/{n_req}"
    makespan = max(p.clock for p in pods)
    for idx, start in span_start.items():
        pod_seconds[0] += max(makespan, vnow[0]) - start
    prompt_tokens = sum(n for p in pods for _, n in p.hit_stats.values())
    cached_tokens = sum(c for p in pods for c, _ in p.hit_stats.values())
    all_ttfts = np.asarray(list(ttfts.values()))
    # Per-QPS-segment tails: reactive autoscaling concedes the FIRST
    # spike (detection needs samples), then holds the repeats — the
    # segment columns are where that shows.
    n_segments = max(segments.values()) + 1
    seg_p99 = [
        round(
            float(
                np.percentile(
                    [ttfts[sid] for sid, s in segments.items() if s == seg],
                    99,
                )
            ),
            4,
        )
        if any(s == seg for s in segments.values())
        else None
        for seg in range(n_segments)
    ]
    itls = np.asarray(
        [
            (p.finish_clock[s.seq_id] - p.first_clock[s.seq_id])
            / (s.num_generated - 1)
            for p in pods
            for s in p.seqs
            if s.num_generated > 1
            and s.seq_id in p.first_clock
            and s.seq_id in p.finish_clock
        ]
    )
    out = {
        "p50_ttft_s": float(np.median(all_ttfts)),
        "p90_ttft_s": float(np.percentile(all_ttfts, 90)),
        "p99_ttft_s": float(np.percentile(all_ttfts, 99)),
        "p50_itl_s": float(np.median(itls)) if itls.size else None,
        "p99_itl_s": float(np.percentile(itls, 99)) if itls.size else None,
        "prefix_cache_hit_rate": (
            float(cached_tokens / prompt_tokens) if prompt_tokens else 0.0
        ),
        "makespan_s": float(makespan),
        "seg_p99_ttft_s": seg_p99,
        "pod_seconds": round(pod_seconds[0], 3),
        "peak_pods": peak_pods[0],
        "pod_pages": fleet_pages,
        "slo_ttft_s": round(slo_t, 4),
        "cold_service_s": round(t_cold, 4),
        **migrations,
        "migration_wall_s": round(migrate_wall[0], 4),
        "pulls": pull_stats["pulls"],
        "pulled_blocks": pull_stats["pulled_blocks"],
        "pull_s": round(pull_stats["pull_s"], 4),
    }
    if dynamic:
        out["actions"] = actions
        out["decisions"] = len(ctl.decisions)
    pods.clear()
    gc.collect()
    return out


def run_tenant_qos_arm(
    workload, tenant_of, params, engine_cfg, max_new_tokens, qos_spec=None,
):
    """ISSUE 18 two-class arm: ONE capacity-constrained pod (tenant QoS
    is a per-pod mechanism) on the virtual clock, serving an interleaved
    premium + background schedule. ``tenant_of(i)`` names request i's
    tenant; ``qos_spec=None`` is the knob-off comparator — the identical
    engine and schedule with no tenant dimension anywhere (requests are
    still sliced by tenant for reporting, the engine never sees it).

    With a spec, the arm drives the PRODUCT machinery end to end: the
    parsed ``TenantQoS`` budget table gates admission on the virtual
    clock (a budget rejection is the 429 arm — the request is shed at
    the door, exactly what the serving layer does), the scheduler runs
    priority ordering + preemption, and the block manager runs
    cache_share accounting with per-tenant MRC slices. Budgets release
    on finish, mirroring ``_forget_pending``; first-prefill hit
    accounting and first-token TTFT stay with the request across
    preemption (same rationale as ``Pod.step_timed``)."""
    from collections import deque as _deque

    from llm_d_kv_cache_manager_tpu.obs.lifecycle import ReuseDistanceEstimator
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.qos import TenantQoS, parse_tenant_qos
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    engine = Engine(engine_cfg, params=params, on_events=lambda _ev: None)
    qos = None
    if qos_spec:
        qos = TenantQoS(parse_tenant_qos(qos_spec))
        engine.scheduler.attach_qos()
        engine.block_manager.attach_qos(qos, mrc_factory=ReuseDistanceEstimator)

    # A throwaway warm-up burst before the timed loop: the in-process
    # trace/dispatch cost of this run's shapes (batched prefill widths,
    # decode widths) is paid once per PROCESS, so without it the cost
    # lands entirely in the FIRST arm's TTFTs and poisons the
    # unloaded/off/on three-way comparison. Concurrent requests exercise
    # the same batch widths the arms hit; the warm chains' pages are
    # untenanted LRU fodder, identical across arms.
    warm_len = len(workload[0][2]) if workload else 8
    wrng = np.random.default_rng(97)
    warm_prompts = [
        wrng.integers(0, engine_cfg.model.vocab_size, warm_len).tolist()
        for _ in range(8)
    ]
    for p in warm_prompts:
        engine.add_request(
            p, SamplingParams(max_new_tokens=max_new_tokens)
        )
    while engine.has_work:
        engine.step()
    # ...and one repeated prompt: the warm-prefill (paged prefix-cache
    # context) dispatch is a DIFFERENT shape than the cold prefills
    # above, and the workloads are built around prefix reuse.
    engine.add_request(
        warm_prompts[0], SamplingParams(max_new_tokens=max_new_tokens)
    )
    while engine.has_work:
        engine.step()

    clock = 0.0
    samples = _deque(maxlen=64)
    seq_tenant = {}  # seq_id -> tenant slice key (arm-side bookkeeping)
    arrivals = {}
    ttfts = {}
    hits = {}  # seq_id -> (cached, prompt) at FIRST prefill
    first_seen = set()
    rejected = {}

    def step():
        nonlocal clock
        t0 = time.perf_counter()
        done = engine.step()
        dt = time.perf_counter() - t0
        if STALL_CAP_X and len(samples) >= 20:
            med = sorted(samples)[len(samples) // 2]
            dt = min(dt, max(med * STALL_CAP_X, 1.0))
        samples.append(dt)
        clock += dt
        for seq in list(engine.scheduler.running) + done:
            if seq.num_generated >= 1 and seq.seq_id not in first_seen:
                first_seen.add(seq.seq_id)
                ttfts[seq.seq_id] = clock - arrivals[seq.seq_id]
                hits[seq.seq_id] = (
                    seq.num_cached_prompt, len(seq.prompt_tokens)
                )
        if qos is not None:
            for seq in done:
                qos.on_resolved(seq.tenant, seq.user_prompt_len)

    for i, (t_arr, _seg, tokens) in enumerate(workload):
        while engine.has_work and clock < t_arr:
            step()
        clock = max(clock, t_arr)
        tenant = tenant_of(i)
        sampling = SamplingParams(max_new_tokens=max_new_tokens)
        if qos is None:
            seq = engine.add_request(tokens, sampling)
        else:
            if qos.admit(tenant, len(tokens), now=clock) is not None:
                rejected[tenant] = rejected.get(tenant, 0) + 1
                continue
            pol = qos.policy(tenant)
            seq = engine.add_request(
                tokens, sampling,
                tenant=tenant, priority=pol.priority, qos_weight=pol.weight,
            )
            qos.on_admitted(tenant, len(tokens), now=clock)
        seq_tenant[seq.seq_id] = tenant
        arrivals[seq.seq_id] = t_arr
    while engine.has_work:
        step()

    def _slice(tenant):
        ids = [s for s, t in seq_tenant.items() if t == tenant]
        lat = [ttfts[s] for s in ids if s in ttfts]
        cached = sum(hits[s][0] for s in ids if s in hits)
        total = sum(hits[s][1] for s in ids if s in hits)
        return {
            "served": len(lat),
            "rejected": rejected.get(tenant, 0),
            "p50_ttft_s": round(float(np.percentile(lat, 50)), 4) if lat else None,
            "p90_ttft_s": round(float(np.percentile(lat, 90)), 4) if lat else None,
            "p99_ttft_s": round(float(np.percentile(lat, 99)), 4) if lat else None,
            "prefix_cache_hit_rate": (
                round(cached / total, 4) if total else None
            ),
        }

    out = {
        "tenants": {
            t: _slice(t)
            for t in sorted(set(seq_tenant.values()) | set(rejected))
        },
        "priority_preempted": engine.lifecycle_stats.get(
            "priority_preempted", 0
        ),
        "makespan_s": round(clock, 4),
    }
    if qos is not None:
        pool = engine_cfg.block_manager.total_pages
        out["cache"] = {
            t: dict(s) for t, s in engine.block_manager.tenant_stats.items()
        }
        # Per-tenant MRC slices: the /debug/mrc sizing evidence — what
        # each tenant's hit rate would be at the pool / half the pool,
        # i.e. the curve an operator reads to size cache_share.
        out["mrc"] = {}
        for t, est in sorted(engine.block_manager._tenant_mrc.items()):
            hit_pool = est.predicted_hit_rate(pool)
            hit_half = est.predicted_hit_rate(max(pool // 2, 1))
            out["mrc"][t] = {
                "predicted_hit_at_pool": (
                    round(hit_pool, 4) if hit_pool is not None else None
                ),
                "predicted_hit_at_half_pool": (
                    round(hit_half, 4) if hit_half is not None else None
                ),
            }
    del engine
    gc.collect()
    return out


def run_kv_integrity_arm(
    workload, params, engine_cfg, max_new_tokens, flips=0, flip_seed=0,
):
    """ISSUE 19 corruption-drill arm: ONE pod, requests served
    SEQUENTIALLY (add → run to completion → next) against a pool sized
    so every warm prefix spills to the host tier between revisits — the
    spill→restore edge the write-time digests guard. Sequential on
    purpose: the trio below is judged on EXACT greedy token parity, and
    the co-sim's Poisson pacing makes batch composition (hence padding
    and reduction order, hence near-tie argmaxes) a function of wall
    time — identical step sequences are what make the parity bar and
    the makespan A/B sound. ``flips`` > 0 injects single-byte flips
    into resident host slots at evenly spaced requests (the same fault
    ``tests/chaos``'s ``corrupt_host_slot`` models: bit rot in the
    spilled copy, invisible until the page is next restored, exported,
    or scrubbed); a final full scrub sweeps whatever latent rot the
    traffic never revisited.

    Returns ``(metrics, outputs)`` — outputs are the per-request greedy
    token ids, so the caller can assert exact parity across the
    off / on-clean / on-drill trio: detection + quarantine + cold
    recompute must serve ZERO corrupted tokens, and the clean knob-on
    run must be bit-identical to the knob-off baseline."""
    from collections import deque as _deque

    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    engine = Engine(engine_cfg, params=params, on_events=lambda _ev: None)
    frng = np.random.default_rng(flip_seed)
    flipped: set[int] = set()

    def flip_host_page() -> int:
        # One byte, one distinct resident chain per injection; quarantined
        # chains are excluded (their host copy is already destroyed).
        engine._flush_page_moves()
        bm = engine.block_manager
        cands = [
            h
            for h in bm._host_cached
            if h not in flipped
            and (
                engine.integrity is None
                or not engine.integrity.is_quarantined(h)
            )
        ]
        if not cands:
            return 0
        h = cands[int(frng.integers(len(cands)))]
        flat = engine._host_k[bm._host_cached[h]].reshape(-1).view(np.uint8)
        flat[int(frng.integers(flat.size))] ^= 0xFF
        flipped.add(h)
        return 1

    # Same rationale as run_tenant_qos_arm's warm-up: pay this pool
    # shape's trace/dispatch cost before the timed loop, so the FIRST of
    # the three runs (the knob-off baseline) isn't charged compile time
    # the other two never see — that would understate the overhead A/B.
    warm_len = len(workload[0][2]) if workload else 8
    wrng = np.random.default_rng(97)
    warm = wrng.integers(0, engine_cfg.model.vocab_size, warm_len).tolist()
    for _ in range(2):
        engine.add_request(warm, SamplingParams(max_new_tokens=max_new_tokens))
        while engine.has_work:
            engine.step()

    clock = 0.0
    samples = _deque(maxlen=64)
    seqs = []
    lat = []
    injected = 0

    def step():
        nonlocal clock
        t0 = time.perf_counter()
        engine.step()
        dt = time.perf_counter() - t0
        if STALL_CAP_X and len(samples) >= 20:
            med = sorted(samples)[len(samples) // 2]
            dt = min(dt, max(med * STALL_CAP_X, 1.0))
        samples.append(dt)
        clock += dt

    cadence = max(len(workload) // (flips + 1), 1) if flips else 0
    for i, (_t, _seg, tokens) in enumerate(workload):
        if flips and injected < flips and i and i % cadence == 0:
            injected += flip_host_page()
        seq = engine.add_request(
            tokens, SamplingParams(max_new_tokens=max_new_tokens)
        )
        seqs.append(seq)
        rt0 = clock
        while engine.has_work:
            step()
        lat.append(clock - rt0)
    if engine.integrity is not None:
        # Final latent-rot sweep: the scrub path's detection, charged to
        # the virtual clock like any other engine work.
        t0 = time.perf_counter()
        engine.scrub_host_pages(1 << 30)
        clock += time.perf_counter() - t0

    out = {
        "p50_request_s": (
            round(float(np.percentile(lat, 50)), 4) if lat else None
        ),
        "p99_request_s": (
            round(float(np.percentile(lat, 99)), 4) if lat else None
        ),
        "makespan_s": round(clock, 4),
        "injected_flips": injected,
        "host": dict(engine.block_manager.host_stats),
        "integrity": (
            engine.integrity.snapshot() if engine.integrity else None
        ),
    }
    outputs = [list(s.output_tokens) for s in seqs]
    del engine
    gc.collect()
    return out, outputs


def run_disagg(
    workload, params, engine_cfg, n_prefill, n_decode, max_new_tokens,
    link_gbps,
):
    """Disaggregated prefill/decode fleet over the same workload: N
    prefill pods run ingest and stop at the first token; each finished
    chain is handed off over the real engine export/import endpoints
    (charged the measured wall time plus the modeled DCN link, exactly
    like BENCH_TRANSFER) and the decode tier streams the remaining
    tokens. Placement is THE PRODUCT PATH (kvcache/router.TwoHopPlanner:
    warmth + measured prefill rate for the prefill hop, queue-depth
    headroom for the decode hop)."""
    from llm_d_kv_cache_manager_tpu.kvcache import (
        KVCacheIndexer,
        KVCacheIndexerConfig,
        PodView,
        TwoHopPlanner,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock import TokenProcessorConfig
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    page = engine_cfg.block_manager.page_size
    indexer = KVCacheIndexer(
        KVCacheIndexerConfig(token_processor=TokenProcessorConfig(block_size=page))
    )
    from llm_d_kv_cache_manager_tpu.obs.audit import (
        RouteAuditor,
        StalenessTracker,
    )

    vnow = [0.0]
    staleness = StalenessTracker(clock=lambda: vnow[0])
    auditor = RouteAuditor(
        index=indexer.kv_block_index,
        model_name=MODEL_NAME,
        ring=len(workload) + 1,
        pending_cap=len(workload) + 1,
    )
    n_pods = n_prefill + n_decode
    pool, publish = make_event_pipeline(
        indexer.kv_block_index, n_pods, staleness=staleness, audit=auditor
    )
    lag_s = float(os.environ.get("BENCH_EVENT_LAG_MS", "2")) / 1000.0
    bus = LaggedEventBus(pool, lag_s)
    pods = [Pod(i, engine_cfg, params, publish, bus) for i in range(n_pods)]
    prefill_pods = {f"tpu-pod-{i}": pods[i] for i in range(n_prefill)}
    decode_pods = {
        f"tpu-pod-{i}": pods[i] for i in range(n_prefill, n_pods)
    }
    planner = TwoHopPlanner(
        score_fn=lambda toks, names: indexer.score_tokens(toks, MODEL_NAME, names)
    )
    link_bytes_s = link_gbps * 1e9 / 8

    def views():
        vs = [
            PodView(
                name, role="prefill", transfer_endpoint=name,
                queue_depth=pod.load, prefill_rate=pod.engine._prefill_rate,
            )
            for name, pod in prefill_pods.items()
        ]
        vs += [
            PodView(name, role="decode", queue_depth=pod.load)
            for name, pod in decode_pods.items()
        ]
        return vs

    ttfts: dict[int, float] = {}
    arrivals: dict[int, float] = {}
    #: prefill-hop seq -> (seq, prompt tokens, source pod, decode pod
    #: name, audit request id)
    pending: dict[int, tuple] = {}
    #: audit rid -> (tier pod object, seq_id) for the realized join; the
    #: ingest entry is the prediction's subject, the decode entry feeds
    #: the both-tier hit accounting.
    ingest_of: dict[str, tuple] = {}
    decode_of: dict[str, tuple] = {}
    handoff = {"count": 0, "blocks": 0, "transfer_s": 0.0, "replans": 0}
    cont_sampling = SamplingParams(max_new_tokens=max_new_tokens - 1)

    def process_handoffs():
        """Move every finished prefill hop's chain to its decode pod and
        admit the continuation there (virtual clocks charged: the decode
        pod cannot admit before the chain existed, nor before its own
        clock, and it pays the measured export/import wall + link time)."""
        for sid in list(pending):
            seq, tokens, src, dec_name, rid = pending[sid]
            if not seq.is_finished():
                continue
            del pending[sid]
            tgt = decode_pods[dec_name]
            hashes = indexer.token_processor.prefix_hashes(tokens)
            t0 = time.perf_counter()
            blocks = src.engine.export_kv_blocks(hashes)
            n_imp = tgt.engine.import_kv_blocks(blocks)
            wall = time.perf_counter() - t0
            wire = sum(b.wire_bytes for b in blocks)
            link_s = wire / link_bytes_s if wire and link_bytes_s else 0.0
            ready_at = src.finish_clock.get(sid, src.clock)
            tgt.clock = max(tgt.clock, ready_at) + wall + link_s
            cont = tgt.engine.add_request(
                tokens + seq.generated_tokens, cont_sampling
            )
            tgt.seqs.append(cont)
            decode_of[rid] = (tgt, cont.seq_id)
            handoff["count"] += 1
            handoff["blocks"] += n_imp
            handoff["transfer_s"] += wall + link_s

    for req_i, (t, _seg, tokens) in enumerate(workload):
        for pod in pods:
            pod.advance_to(t, ttfts, arrivals)
        process_handoffs()
        vnow[0] = t
        bus.release(t)
        plan = planner.plan(tokens, views())
        src = prefill_pods[plan.prefill_pod]
        dec_name = plan.decode_pod
        rid = f"req-{req_i}"
        # The planner's warmth claim for the ingest hop IS the prediction
        # under audit; realized comes from the prefill pod's first-prefill
        # hit accounting below.
        auditor.record_decision(
            rid,
            chosen_pod=plan.prefill_pod,
            predicted_blocks=plan.prefill_score,
            index_blocks=plan.prefill_score,
            scoreboard={plan.prefill_pod: plan.prefill_score},
            decision="disagg",
            chain_hashes=indexer.token_processor.prefix_hashes(tokens),
        )
        if not src.engine.has_work:
            src.clock = max(src.clock, t)
        seq = src.engine.add_request(tokens, SamplingParams(max_new_tokens=1))
        src.seqs.append(seq)
        arrivals[seq.seq_id] = t
        pending[seq.seq_id] = (seq, tokens, src, dec_name, rid)
        ingest_of[rid] = (src, seq.seq_id)
    while True:
        for pod in pods:
            pod.drain(ttfts, arrivals)
        process_handoffs()
        if not pending and not any(p.engine.has_work for p in pods):
            break
    vnow[0] = max(p.clock for p in pods)
    bus.flush_all()
    pool.drain(timeout=10.0)
    for rid, (src, sid) in ingest_of.items():
        if sid in src.hit_stats:
            auditor.record_realized(
                rid, f"tpu-pod-{src.pod_id}", src.hit_stats[sid][0] // page
            )
    pool.shutdown()
    indexer.shutdown()

    n_req = len(workload)
    assert len(ttfts) == n_req, f"lost requests: {len(ttfts)}/{n_req}"
    all_ttfts = np.asarray(list(ttfts.values()))
    makespan = max(p.clock for p in pods)
    # Decode-tier ITL: the isolation headline — continuation lanes never
    # share an engine with 2k-token ingest, so their inter-token gaps are
    # pure decode cadence (plus the handoff's own admission prefill).
    itls = np.asarray(
        [
            (p.finish_clock[s.seq_id] - p.first_clock[s.seq_id])
            / (s.num_generated - 1)
            for p in decode_pods.values()
            for s in p.seqs
            if s.num_generated > 1
            and s.seq_id in p.first_clock
            and s.seq_id in p.finish_clock
        ]
    )
    # Realized cache behavior, BOTH tiers via the audit path (the r08
    # record counted the ingest tier only — a decode-hop handoff that
    # failed to cache-hit its imported chain was invisible). The tiers
    # answer different questions and are reported separately: the ingest
    # rate is the workload's shared-prefix reuse (comparable to the mixed
    # arms' definition), the decode rate is handoff efficiency (~1.0 when
    # every imported chain is hit; a drop means the transfer fabric
    # delivered chains the decode engine then recomputed). The headline
    # `prefix_cache_hit_rate` is the combined both-tier number.
    ingest_prompt = sum(
        n for p in prefill_pods.values() for _, n in p.hit_stats.values()
    )
    ingest_cached = sum(
        c for p in prefill_pods.values() for c, _ in p.hit_stats.values()
    )
    decode_prompt = decode_cached = 0
    for tgt, sid in decode_of.values():
        if sid in tgt.hit_stats:
            c, n = tgt.hit_stats[sid]
            decode_cached += c
            decode_prompt += n
    prompt_tokens = ingest_prompt + decode_prompt
    cached_tokens = ingest_cached + decode_cached
    out_tokens = sum(len(s.output_tokens) for p in pods for s in p.seqs)
    res = {
        "n_prefill": n_prefill,
        "n_decode": n_decode,
        "p50_ttft_s": float(np.median(all_ttfts)),
        "p90_ttft_s": float(np.percentile(all_ttfts, 90)),
        "p50_itl_s": float(np.median(itls)) if itls.size else None,
        "p90_itl_s": float(np.percentile(itls, 90)) if itls.size else None,
        "p99_itl_s": float(np.percentile(itls, 99)) if itls.size else None,
        "req_s_per_chip": float(n_req / makespan / n_pods) if makespan else 0.0,
        "output_tok_s_per_chip": (
            float(out_tokens / makespan / n_pods) if makespan else 0.0
        ),
        "prefix_cache_hit_rate": (
            float(cached_tokens / prompt_tokens) if prompt_tokens else 0.0
        ),
        "ingest_hit_rate": (
            float(ingest_cached / ingest_prompt) if ingest_prompt else 0.0
        ),
        "decode_hit_rate": (
            float(decode_cached / decode_prompt) if decode_prompt else None
        ),
        "makespan_s": float(makespan),
        "handoffs": handoff["count"],
        "handoff_blocks": handoff["blocks"],
        "handoff_transfer_s": round(handoff["transfer_s"], 3),
        "staleness": {
            "events": staleness.snapshot()["events_observed"],
            "p50_ms": (
                round(staleness.percentiles()["p50"] * 1000, 3)
                if staleness.percentiles()["p50"] is not None
                else None
            ),
            "p99_ms": (
                round(staleness.percentiles()["p99"] * 1000, 3)
                if staleness.percentiles()["p99"] is not None
                else None
            ),
        },
        "audit": _audit_summary(auditor),
    }
    pods.clear()
    gc.collect()
    return res


def lifecycle_overhead_ab(params, engine_cfg, workload, max_new_tokens):
    """ISSUE 15 overhead A/B: per-engine-step wall time with the full
    OBS_LIFECYCLE + OBS_FLIGHT instrumentation attached (step timing,
    ledger, MRC, per-step flight recording — everything the serving loop
    pays with the knobs on) vs the bare legacy engine, on an identical
    single-engine request stream. The acceptance bar is knobs-on step
    p50 within 2% of knobs-off."""
    from llm_d_kv_cache_manager_tpu.obs.flight import FlightRecorder
    from llm_d_kv_cache_manager_tpu.obs.lifecycle import (
        BlockLifecycleLedger,
        ReuseDistanceEstimator,
    )
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    reqs = [tokens for _, _, tokens in workload[:24]]
    p50 = {}
    lanes = max(engine_cfg.decode_batch_size, 1)
    for mode in ("off", "on"):
        eng = Engine(engine_cfg, params=params)
        flight = None
        if mode == "on":
            eng.obs_step_timing = True
            eng.block_manager.attach_lifecycle(
                BlockLifecycleLedger(), ReuseDistanceEstimator()
            )
            flight = FlightRecorder()
        steps = []
        for tokens in reqs:
            eng.add_request(tokens, SamplingParams(max_new_tokens=max_new_tokens))
            while eng.has_work:
                t0 = time.perf_counter()
                eng.step()
                steps.append(time.perf_counter() - t0)
                if flight is not None:
                    # The serving loop's per-step flight work, replayed
                    # faithfully so the A/B charges it too.
                    flight.record_step(
                        eng.step_stats,
                        occupancy=len(eng.scheduler.running) / lanes,
                        free_pages=eng.block_manager.num_free,
                    )
        p50[mode] = float(np.median(steps))
        n_steps = len(steps)
        del eng
        gc.collect()
    return {
        "requests": len(reqs),
        "steps": n_steps,
        "p50_step_off_s": round(p50["off"], 6),
        "p50_step_on_s": round(p50["on"], 6),
        "p50_on_over_off": (
            round(p50["on"] / p50["off"], 4) if p50["off"] else None
        ),
    }


def obs_fed_overhead_ab(params, engine_cfg, workload, max_new_tokens):
    """ISSUE 20 overhead A/B: (a) the headline — 4-pod
    ``FleetFederator.scrape()`` join latency (p50/p99 over ~200 scrapes
    against in-process pods carrying realistic fully-loaded payloads:
    three tiers, SLO burn, tenant slices, integrity, MRC/lifecycle/audit
    surfaces); (b) per-engine-step wall time with a background scraper
    thread hammering a federator whose fetch hooks read the LIVE engine
    state during stepping, vs the bare engine on an identical stream.
    The scraper runs at ~10 Hz — an order above any real deployment's
    scrape cadence, and strictly pessimistic beyond that: it shares the
    engine's process (and GIL), which a deployed scorer-side federator
    never does. The bar: knobs-on step p50 within 2% of knobs-off."""
    import threading

    from llm_d_kv_cache_manager_tpu.obs.federation import FleetFederator
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    # -- headline: 4-pod snapshot join latency ---------------------------
    def stub_fetch(seed):
        # One pod's surfaces, every presence-gated block populated so the
        # join pays its full price (legacy pods would be cheaper).
        stats = {
            "model": "bench/llama",
            "total_pages": 1024,
            "free_pages": 128 + seed,
            "staged": 2,
            "waiting": 3,
            "running": 8,
            "host": {"cached": 512, "host_pages": 2048},
            "remote": {"store_cached": 256, "store_pages": 4096},
            "prefill": {"cached_tokens": 40960 + seed, "computed_tokens": 8192},
            "drain": {"draining": False},
            "transfer": {
                "breakers": {
                    f"tcp://pod-{j}:5558": {"state": "closed"}
                    for j in range(4)
                }
            },
            "slo": {
                "burn_rates": {
                    "ttft": {"5m": 0.4, "1h": 0.2},
                    "itl": {"5m": 0.1, "1h": 0.05},
                }
            },
            "tenant_qos": {
                "slo_burn": {"premium": {"ttft": {"5m": 0.3}}},
                "cache": {
                    "stats": {
                        "premium": {"pages": 300, "share": 0.3},
                        "batch": {"pages": 596, "share": 0.6},
                    }
                },
            },
            "integrity": {
                "quarantined": 0,
                "checks_corrupt": 0,
                "bad_blocks_published": 0,
            },
            "flight": {
                "triggers": 1,
                "events_recorded": 2048,
                "dumps_written": 1,
            },
        }
        surfaces = {
            "/stats": stats,
            "/debug/mrc": {
                "enabled": True,
                "sampled": 4096,
                "cold_fraction": 0.12,
                "curve": [
                    {"pages": c, "miss_ratio": round(1.0 - c / 1100, 4)}
                    for c in range(64, 1025, 64)
                ],
            },
            "/debug/lifecycle": {
                "enabled": True,
                "transitions_recorded": 10000 + seed,
            },
            "/debug/audit": {
                "enabled": True,
                "joined": 512,
                "miss_causes": {"cold": 30, "evicted": 10, "stale_index": 2},
            },
            "/debug/staleness": None,
        }
        return lambda path: surfaces.get(path)

    fed = FleetFederator(ring=256)
    for i in range(4):
        fed.register_pod(f"bench-p{i}", fetch=stub_fetch(i))
    joins = []
    for _ in range(200):
        t0 = time.perf_counter()
        fed.scrape()
        joins.append(time.perf_counter() - t0)
    join_p50 = float(np.percentile(joins, 50))
    join_p99 = float(np.percentile(joins, 99))

    # -- step A/B: bare engine vs engine + live-state scrape hammer ------
    # The stream is repeated 3x: the instrument under test costs well
    # under 1% duty cycle, so the median needs enough steps to resolve
    # it from smoke-scale CPU jitter (a 36-step median wanders +-3%
    # run-to-run on its own — see lifecycle_overhead_ab across records).
    reqs = [tokens for _, _, tokens in workload[:24]] * 3
    total_pages = engine_cfg.block_manager.total_pages
    p50 = {}
    scrapes_on = 0
    for mode in ("off", "on"):
        eng = Engine(engine_cfg, params=params)
        stop = scraper = None
        if mode == "on":
            def live_stats():
                # What a real in-process fetch hook reads mid-step: the
                # live pool/scheduler counters, no locks the step path
                # holds.
                return {
                    "model": "bench/llama",
                    "total_pages": total_pages,
                    "free_pages": eng.block_manager.num_free,
                    "running": len(eng.scheduler.running),
                    "prefill": dict(getattr(eng, "prefill_stats", {}) or {}),
                    "drain": {"draining": False},
                }

            def live_fetch(path):
                return live_stats() if path == "/stats" else None

            live = FleetFederator(ring=256)
            for i in range(4):
                live.register_pod(f"live-p{i}", fetch=live_fetch)
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    live.scrape()
                    stop.wait(0.1)

            scraper = threading.Thread(
                target=hammer, name="bench-fed-scraper", daemon=True
            )
            scraper.start()
        steps = []
        for tokens in reqs:
            eng.add_request(tokens, SamplingParams(max_new_tokens=max_new_tokens))
            while eng.has_work:
                t0 = time.perf_counter()
                eng.step()
                steps.append(time.perf_counter() - t0)
        if stop is not None:
            stop.set()
            scraper.join(timeout=5)
            scrapes_on = live.snapshot()["scrapes"]
        p50[mode] = float(np.median(steps))
        n_steps = len(steps)
        del eng
        gc.collect()
    return {
        "requests": len(reqs),
        "steps": n_steps,
        "join_pods": 4,
        "join_iters": len(joins),
        "join_p50_s": round(join_p50, 6),
        "join_p99_s": round(join_p99, 6),
        "scrapes_during_on": scrapes_on,
        "p50_step_off_s": round(p50["off"], 6),
        "p50_step_on_s": round(p50["on"], 6),
        "p50_on_over_off": (
            round(p50["on"] / p50["off"], 4) if p50["off"] else None
        ),
    }


def warmup(params, engine_cfg, prefix_len, suffix_len, vocab, max_new_tokens):
    """Compile every jit shape the measured runs will hit (cold prefill,
    warm suffix-only prefill, mixed batch, decode) on a scratch engine."""
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    rng = np.random.default_rng(1234)
    eng = Engine(engine_cfg, params=params)
    prefix = rng.integers(0, vocab, prefix_len).tolist()

    def req():
        return eng.add_request(
            prefix + rng.integers(0, vocab, suffix_len).tolist(),
            SamplingParams(max_new_tokens=max_new_tokens),
        )

    req()  # cold: (chunk=full, ctx=0)
    eng.run_until_complete()
    req()  # warm: (chunk=suffix bucket, ctx=max)
    eng.run_until_complete()
    cold = rng.integers(0, vocab, prefix_len + suffix_len).tolist()
    eng.add_request(cold, SamplingParams(max_new_tokens=max_new_tokens))
    req()  # mixed cold+warm batch: (chunk=full, ctx=max)
    eng.run_until_complete()


def main() -> int:
    import jax
    import jax.numpy as jnp

    from llm_d_kv_cache_manager_tpu.models import llama
    from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig
    from llm_d_kv_cache_manager_tpu.server.block_manager import BlockManagerConfig
    from llm_d_kv_cache_manager_tpu.server.engine import EngineConfig
    from llm_d_kv_cache_manager_tpu.server.scheduler import SchedulerConfig

    on_tpu = jax.default_backend() == "tpu"
    smoke = os.environ.get("BENCH_SMOKE") == "1" or not on_tpu
    quantize = None
    bench_model = os.environ.get("BENCH_MODEL", "1p4b")
    assert bench_model in ("1p4b", "8b-int8"), bench_model
    if bench_model == "8b-int8" and smoke:
        raise SystemExit(
            "BENCH_MODEL=8b-int8 needs the TPU backend (smoke/CPU would "
            "silently run the tiny config under the 8B label)"
        )

    if smoke:
        model_label = "tiny"
        model_cfg = llama.TINY_LLAMA
        n_pods, n_groups, reqs_per_group = 2, 4, 3
        prefix_len, suffix_len, max_new = 64, 16, 4
        total_pages, page = 256, 16
        decode_burst = 2
        interpret = not on_tpu
    elif bench_model == "8b-int8":
        model_label = bench_model
        # North-star scale: the REAL Llama-3-8B architecture, int8 weights
        # (one shared copy, ~8.3 GB) + 2 pods' KV pools on one chip.
        model_cfg = llama.LLAMA_3_8B
        quantize = "int8"
        n_pods, n_groups, reqs_per_group = 2, 8, 5
        prefix_len, suffix_len, max_new = 2048, 48, 16
        total_pages, page = 1024, 16
        decode_burst = 8
        interpret = False
    else:
        model_label = bench_model  # "1p4b"
        # Llama-3-8B-family architecture scaled (1.4B) so a 4-pod fleet
        # (one weight copy + 4 KV pools) fits one v5e chip while cold
        # prefills stay compute-bound — the analogue of the reference's
        # 8k-prefix/70B capacity runs. An unscaled-8B (int8) single-engine
        # number lives in benchmarking/results/engine_throughput.md.
        model_cfg = LlamaConfig(
            vocab_size=32_000,
            hidden_size=3072,
            intermediate_size=8192,
            n_layers=12,
            n_heads=24,
            n_kv_heads=8,
            rope_scaling=llama.LLAMA_3_8B.rope_scaling,
            dtype=jnp.bfloat16,
        )
        n_pods, n_groups, reqs_per_group = 4, 32, 8
        prefix_len, suffix_len, max_new = 4096, 48, 16
        # Pool sized so a precise pod's share of prefixes (~8 groups ×
        # 257 pages) stays resident while a round-robin pod (which sees
        # all 32 prefixes) thrashes its prefix cache — the regime of the
        # reference's capacity benchmarks.
        total_pages, page = 2560, 16
        decode_burst = 8
        interpret = False

    host_pages = int(os.environ.get("BENCH_HOST_PAGES", "0"))
    total_pages = int(os.environ.get("BENCH_TOTAL_PAGES", total_pages))
    n_groups = int(os.environ.get("BENCH_GROUPS", n_groups))
    reqs_per_group = int(os.environ.get("BENCH_REQS_PER_GROUP", reqs_per_group))
    prefix_len = int(os.environ.get("BENCH_PREFIX_LEN", prefix_len))
    policies = tuple(
        os.environ.get("BENCH_POLICIES", ",".join(ALL_POLICIES)).split(",")
    )
    assert all(p in RUNNABLE_POLICIES for p in policies), policies

    max_len = prefix_len + suffix_len + max_new + page
    chunked = int(os.environ.get("BENCH_CHUNKED_PREFILL_TOKENS", 0))
    # Host-tier arm knobs (ISSUE 6): paged-KV quantization on spill, the
    # ahead-of-scheduler prefetch stage, and tier admission policy. They
    # bind wherever a config carries host_pages > 0 (the main pass with
    # BENCH_HOST_PAGES, and the pressure pass's precise_host arm).
    kv_quant = os.environ.get("BENCH_KV_QUANT", "int8") or None
    host_prefetch = os.environ.get("BENCH_HOST_PREFETCH", "1") == "1"
    host_tier_policy = os.environ.get("BENCH_HOST_TIER_POLICY", "always")
    # Decode fast path (ISSUE 7): device-resident last tokens across steps
    # + async D2H of sampled ids, on EVERY arm's engines so the policy
    # comparison stays apples-to-apples.
    decode_fastpath = os.environ.get("BENCH_DECODE_FASTPATH", "0") == "1"
    spec_mode = os.environ.get("BENCH_SPEC_DECODE", "") or None
    engine_cfg = EngineConfig(
        model=model_cfg,
        block_manager=BlockManagerConfig(
            total_pages=total_pages, page_size=page, host_pages=host_pages
        ),
        kv_quant=kv_quant if host_pages > 0 else None,
        host_prefetch=host_prefetch and host_pages > 0,
        host_tier_policy=host_tier_policy if host_pages > 0 else "auto",
        scheduler=SchedulerConfig(
            max_prefill_batch=4,
            max_prefill_tokens=8192,
            chunked_prefill_tokens=chunked if chunked > 0 else None,
        ),
        max_model_len=max_len,
        decode_batch_size=8,
        decode_steps_per_iter=decode_burst,
        decode_pipeline=decode_fastpath,
        decode_fused_sampling=decode_fastpath,
        prefill_bucket=64,
        # Pin warm prefills AND decode tables to a single width → one
        # compiled shape each. Mid-run XLA compiles (~30-60s on this model)
        # otherwise land in whichever pod's virtual clock hits a fresh
        # decode width first, blowing up its tail latencies.
        prefill_ctx_bucket=-(-max_len // page),
        decode_pages_bucket=-(-max_len // page),
        interpret=interpret,
    )

    params = llama.init_params(jax.random.PRNGKey(0), model_cfg, quantize=quantize)
    jax.block_until_ready(params)

    warmup(params, engine_cfg, prefix_len, suffix_len, model_cfg.vocab_size, max_new)
    gc.collect()  # scratch engine's KV pool must be gone before the fleet

    # Calibrate the arrival rate off the measured cold-request service time
    # so the middle of the QPS ramp saturates round-robin (its regime in
    # the reference benchmarks: random/RR explodes to ~85 s TTFT while
    # precise stays sub-second) without hand-tuned absolute QPS.
    from llm_d_kv_cache_manager_tpu.server.engine import Engine
    from llm_d_kv_cache_manager_tpu.server.sequence import SamplingParams

    cal_rng = np.random.default_rng(7)
    cal_eng = Engine(engine_cfg, params=params)
    batch_w = engine_cfg.scheduler.max_prefill_batch
    t0 = time.perf_counter()
    for _ in range(batch_w):
        cal_eng.add_request(
            cal_rng.integers(0, model_cfg.vocab_size, prefix_len + suffix_len).tolist(),
            SamplingParams(max_new_tokens=max_new),
        )
    cal_eng.run_until_complete()
    t_cold = (time.perf_counter() - t0) / batch_w  # per-request, batched cold
    del cal_eng  # release its KV pool before building the fleet
    gc.collect()
    qps_mid = 1.4 * n_pods / max(t_cold, 1e-4)
    scales = [
        float(s)
        for s in os.environ.get("BENCH_QPS_SCALES", "0.7,1.0,1.4").split(",")
    ]
    qps_ramp = [qps_mid * s for s in scales]

    rng = np.random.default_rng(42)
    workload = build_workload(
        rng, n_groups, reqs_per_group, prefix_len, suffix_len,
        model_cfg.vocab_size, qps_ramp,
    )

    results = {}
    for policy in policies:
        results[policy] = run_policy(
            policy, workload, params, engine_cfg, n_pods, max_new
        )

    # Speculative-decode arm (BENCH_SPEC_DECODE=prompt_lookup): precise
    # routing with the prompt-lookup speculative path live in every pod
    # engine — graduated from dryrun-only to a measured arm with an
    # acceptance-rate column.
    if spec_mode and "precise" in policies:
        import dataclasses as _dc

        spec_cfg = _dc.replace(engine_cfg, spec_decode=spec_mode)
        results["precise_spec"] = run_policy(
            "precise", workload, params, spec_cfg, n_pods, max_new
        )

    # -- Pressure regime (the product's differentiator) -------------------
    # Under an ample pool, index-free affinity ("estimated") ties precise:
    # nothing it believes about pod caches is ever wrong. The index's
    # reason to exist is EVICTION AWARENESS, which only shows when pods
    # actually evict — the reference's own headline regime
    # (37-capacity/README.md:235-238: precise p90 0.275 s vs estimated
    # 7.5 s at capacity). Re-run rr/estimated/precise on the same workload
    # with the pool shrunk past the working set so the round record
    # carries both regimes (results/routing_capacity.md measured
    # estimated's p90 ~1.9x worse there).
    pressure_results = {}
    pressure_pages = 0
    pressure_host_pages = 0
    if os.environ.get("BENCH_PRESSURE", "1") == "1":
        # Smoke fallback is total_pages/16, not /2: the tiny workload's
        # working set is so small that a half-size pool never evicts, and
        # a pressure pass with zero evictions (hence zero spills in the
        # host arm) exercises nothing.
        default_pp = {"1p4b": 1536, "8b-int8": 640}.get(
            model_label, max(total_pages // 16, 16)
        )
        pressure_pages = int(os.environ.get("BENCH_PRESSURE_PAGES", default_pp))
        import dataclasses

        pressure_cfg = dataclasses.replace(
            engine_cfg,
            block_manager=dataclasses.replace(
                engine_cfg.block_manager, total_pages=pressure_pages
            ),
        )
        #: every pressure arm as (policy, config, remote) so the first
        #: run and the BENCH_REPEATS re-runs execute identically.
        pressure_arms: dict[str, tuple] = {}
        for policy in ("round_robin", "estimated", "precise"):
            if policy in policies:
                pressure_arms[policy] = (policy, pressure_cfg, False)
        # Host-tier + int8-KV-spill arm (ISSUE 6): precise routing under
        # the SAME shrunken HBM pool, but evictions spill (quantized) to a
        # host-DRAM tier and waiting sequences' host-cached prefixes are
        # prefetched back ahead of the scheduler — the ">=2x effective
        # pages" capacity claim, measured in the regime where routing
        # alone stopped helping (r05).
        pressure_host_pages = int(
            os.environ.get("BENCH_PRESSURE_HOST_PAGES", str(pressure_pages))
        )
        if "precise" in policies and pressure_host_pages > 0:
            host_cfg = dataclasses.replace(
                pressure_cfg,
                block_manager=dataclasses.replace(
                    pressure_cfg.block_manager,
                    host_pages=pressure_host_pages,
                ),
                kv_quant=kv_quant,
                host_prefetch=host_prefetch,
                host_tier_policy=host_tier_policy,
            )
            pressure_arms["precise_host"] = ("precise", host_cfg, False)
        # Remote-tier arm (ISSUE 13): precise routing under the SAME
        # shrunken HBM pool with NO host tier — last-copy evictions demote
        # (int8 wire) to the kvstore holder and the router pulls them
        # back, so the fleet-wide pool, not the per-pod pool, bounds the
        # working set. The regime where the host tier plateaued at the
        # single-pod ceiling (hit 0.533, r06) is exactly where this arm
        # must push the hit rate back toward the unpressured number.
        if (
            "precise" in policies
            and os.environ.get("BENCH_REMOTE_TIER", "0") == "1"
        ):
            remote_cfg = dataclasses.replace(
                pressure_cfg,
                kv_quant=kv_quant,
                remote_tier=True,
            )
            pressure_arms["precise_remote"] = ("precise", remote_cfg, True)
        # Quantized-HBM arm (ISSUE 16): precise routing under the SAME
        # HBM byte budget as the bare pressure pool, but KV_QUANT_HBM=int8
        # halves the bytes per page, so those bytes hold 2x the pages.
        # The unquantized arm's MRC forecast at the 2x capacity point
        # (mrc_predicted_hit_2x, pre-registered in BENCH_r14.json before
        # the kernel landed) is the number this arm's measured hit must
        # land within 0.05 of.
        if (
            "precise" in policies
            and os.environ.get("BENCH_KV_QUANT_HBM", "0") == "1"
        ):
            hbm_q8_cfg = dataclasses.replace(
                pressure_cfg,
                block_manager=dataclasses.replace(
                    pressure_cfg.block_manager,
                    total_pages=2 * pressure_pages,
                ),
                kv_quant_hbm="int8",
            )
            pressure_arms["precise_hbm_q8"] = ("precise", hbm_q8_cfg, False)
        for name, (policy, cfg_, rmt) in pressure_arms.items():
            # MRC estimators ride every pressure arm (ISSUE 15): the
            # forced-eviction regime is where predicted-vs-measured
            # capacity modeling is falsifiable.
            pressure_results[name] = run_policy(
                policy, workload, params, cfg_, n_pods, max_new, remote=rmt,
                mrc=True,
            )
        # Interpret-mode variance control (r09 note): on CPU smoke the
        # estimated/precise p90 race swings 0.485↔1.038 between rounds on
        # timing jitter alone. BENCH_REPEATS > 1 re-runs every pressure
        # arm except round_robin and reports MEDIAN hit-rate fields (the
        # ISSUE 13 >=0.8 acceptance number is a median, not a single-shot
        # draw) plus the estimated/precise p90 race median with spread.
        # Default 1 = the legacy single-round output, field for field.
        pressure_race_ratios = []
        pressure_hits: dict[str, list] = {
            name: [res["prefix_cache_hit_rate"]]
            for name, res in pressure_results.items()
        }
        #: per-arm MRC predicted-hit samples across repeat rounds (the
        #: validation compares MEDIANS on both sides of the claim)
        pressure_mrc: dict[str, dict[str, list]] = {
            name: {
                cap: [v]
                for cap, v in res.get("mrc", {})
                .get("predicted_hit", {})
                .items()
                if v is not None
            }
            for name, res in pressure_results.items()
        }
        #: per-arm TTFT/ITL percentile samples across the repeat rounds
        #: (ISSUE 14 satellite: the latency race fields become medians
        #: too, with a spread block — a single CPU-jitter draw stops
        #: masquerading as a latency signal)
        LAT_KEYS = (
            "p50_ttft_s", "p90_ttft_s", "p99_ttft_s",
            "p50_itl_s", "p90_itl_s", "p99_itl_s",
        )
        pressure_lat: dict[str, dict[str, list]] = {
            name: {
                k: [res[k]] for k in LAT_KEYS if res.get(k) is not None
            }
            for name, res in pressure_results.items()
        }
        repeats = int(os.environ.get("BENCH_REPEATS", "1"))

        def race_ratio(est, prec):
            return (
                est["p90_ttft_s"] / prec["p90_ttft_s"]
                if prec["p90_ttft_s"] > 0
                else None
            )

        if repeats > 1:
            if "estimated" in pressure_results and "precise" in pressure_results:
                r0 = race_ratio(
                    pressure_results["estimated"], pressure_results["precise"]
                )
                if r0 is not None:
                    pressure_race_ratios.append(r0)
            for _ in range(repeats - 1):
                round_res = {}
                for name, (policy, cfg_, rmt) in pressure_arms.items():
                    if name == "round_robin":
                        continue
                    round_res[name] = run_policy(
                        policy, workload, params, cfg_, n_pods, max_new,
                        remote=rmt, mrc=True,
                    )
                    pressure_hits[name].append(
                        round_res[name]["prefix_cache_hit_rate"]
                    )
                    for cap, v in (
                        round_res[name]
                        .get("mrc", {})
                        .get("predicted_hit", {})
                        .items()
                    ):
                        if v is not None:
                            pressure_mrc[name].setdefault(cap, []).append(v)
                    for k in LAT_KEYS:
                        if round_res[name].get(k) is not None:
                            pressure_lat[name].setdefault(k, []).append(
                                round_res[name][k]
                            )
                if "estimated" in round_res and "precise" in round_res:
                    r = race_ratio(round_res["estimated"], round_res["precise"])
                    if r is not None:
                        pressure_race_ratios.append(r)

    # -- Lifecycle/flight overhead A/B (ISSUE 15) -------------------------
    # Same engine, same stream, instruments on vs off: the observability
    # plane's acceptance includes NOT taxing the hot path (knobs-on step
    # p50 within 2% of knobs-off).
    overhead_ab = None
    if os.environ.get("BENCH_LIFECYCLE_AB", "1") == "1":
        overhead_ab = lifecycle_overhead_ab(
            params, engine_cfg, workload, max_new
        )

    # -- Disaggregated prefill/decode arm (ISSUE 9) -----------------------
    # Same workload, same total pod count, but the fleet is split into a
    # prefill tier (ingest at full batch width, stop at first token) and a
    # decode tier (pull the chain, stream tokens). The comparison against
    # the mixed `precise` fleet is the isolation headline: decode-tier ITL
    # with ingest REMOVED from decode engines vs merely chunked/batched in.
    disagg_result = None
    n_disagg_prefill = 0
    if os.environ.get("BENCH_DISAGG", "0") == "1":
        n_disagg_prefill = int(
            os.environ.get(
                "BENCH_DISAGG_PREFILL_PODS", str(max(n_pods // 2, 1))
            )
        )
        n_disagg_prefill = min(max(n_disagg_prefill, 1), n_pods - 1)
        disagg_result = run_disagg(
            workload, params, engine_cfg,
            n_disagg_prefill, n_pods - n_disagg_prefill, max_new,
            link_gbps=float(os.environ.get("BENCH_TRANSFER_GBPS", "10")),
        )

    # -- Workload-generator family + predicted-TTFT arm (ISSUE 14) --------
    # Four traffic shapes beyond the steady shared-prefix ramp, each run
    # under round_robin / precise / predicted. The burst and ramp arms
    # are the acceptance regime: pile-on traffic where score-max queues
    # behind the warm pod and predicted-TTFT routing must win BOTH tails
    # while holding hit-rate parity with precise.
    family_results = None
    family_spreads = None
    fam_repeats = int(os.environ.get("BENCH_REPEATS", "1"))
    if os.environ.get("BENCH_WORKLOAD_FAMILY", "1") == "1":
        import statistics as _stats

        fam_groups = n_groups if smoke else max(n_groups // 2, 2)
        # ~48 requests per smoke arm: enough for queues to form in the
        # bursts and for p99 to mean something, small enough that the
        # 4-arm x 3-policy grid stays a smoke.
        fam_reqs = (
            max(-(-48 // fam_groups), 2)
            if smoke
            else max(reqs_per_group // 2, 2)
        )
        # A 2-pod fleet makes balance-vs-warmth nearly zero-sum; the
        # family judges routing POLICY separation, which needs enough
        # pods for round_robin to scatter prefixes and precise to pile
        # on. Smoke engines are tiny, so widen the fleet there.
        fam_pods = max(n_pods, 4) if smoke else n_pods
        fam_qps = qps_mid * fam_pods / n_pods
        fam_rng = np.random.default_rng(1412)
        fam_workloads = {
            # Square-wave bursts over a quiet baseline: the thundering-
            # herd regime where warmth-first routing pays more in queue
            # time than it saves in prefill.
            "burst": build_workload(
                fam_rng, fam_groups, fam_reqs, prefix_len, suffix_len,
                model_cfg.vocab_size,
                [fam_qps * s for s in (0.7, 5.0, 0.7, 5.0, 0.7)],
            ),
            # Diurnal rise-and-fall.
            "ramp": build_workload(
                fam_rng, fam_groups, fam_reqs, prefix_len, suffix_len,
                model_cfg.vocab_size,
                [fam_qps * s for s in (0.4, 0.9, 1.4, 0.9, 0.4)],
            ),
            # Multi-turn sessions: turn k+1 extends turn k's prefix.
            "session": build_session_workload(
                fam_rng,
                n_sessions=max(fam_groups * fam_reqs // 4, 2),
                turns=4,
                prefix_len=prefix_len,
                suffix_len=suffix_len,
                vocab=model_cfg.vocab_size,
                qps=fam_qps,
            ),
            # Agent swarm: waves of one deep shared prefix.
            "swarm": build_swarm_workload(
                fam_rng,
                n_agents=max(fam_groups, 4),
                waves=max(fam_reqs, 2),
                prefix_len=prefix_len,
                suffix_len=suffix_len,
                vocab=model_cfg.vocab_size,
                qps=fam_qps,
            ),
        }
        fam_lat_keys = (
            "p50_ttft_s", "p90_ttft_s", "p99_ttft_s",
            "p50_itl_s", "p90_itl_s", "p99_itl_s",
            "prefix_cache_hit_rate",
        )
        family_results = {}
        family_spreads = {}
        for wname, wl in fam_workloads.items():
            per_pol = {}
            spread_pol = {}
            for pol in ("round_robin", "precise", "predicted"):
                # MEDIANS are what the acceptance is judged on, so the
                # repeat budget goes to the acceptance arms; the color
                # arms (session, swarm) run single-shot.
                n_rounds = (
                    fam_repeats if wname in ("burst", "ramp") else 1
                )
                rounds = [
                    run_policy(pol, wl, params, engine_cfg, fam_pods, max_new)
                    for _ in range(n_rounds)
                ]
                # MEDIANS over the repeat rounds for the percentile
                # fields (the ISSUE 14 acceptance comparison must not be
                # a single draw); the rest of the detail (audit columns,
                # hit accounting) is the last round's.
                res = dict(rounds[-1])
                spread = {}
                for k in fam_lat_keys:
                    vals = [r[k] for r in rounds if r.get(k) is not None]
                    if vals:
                        res[k] = float(_stats.median(vals))
                        if len(vals) > 1:
                            spread[k] = {
                                "rounds": len(vals),
                                "min": round(min(vals), 4),
                                "max": round(max(vals), 4),
                            }
                per_pol[pol] = res
                if spread:
                    spread_pol[pol] = spread
            family_results[wname] = per_pol
            if spread_pol:
                family_spreads[wname] = spread_pol

    # -- Fleet controller arm (ISSUE 17): pod count in the loop ----------
    # The family re-judged as an AUTOSCALING problem: the same four
    # traffic shapes served twice on identical capacity-constrained
    # engines — once by a fleet pinned at the burst peak (what a planner
    # provisions statically), once starting at one pod under the product
    # FleetController (scale-up on burn x MRC headroom with warm-set
    # revival, scale-down by live migration). The verdict column is
    # pod-seconds at comparable tail latency.
    fleet_detail = None
    if (
        os.environ.get("BENCH_FLEET", "1") == "1"
        and family_results is not None
    ):
        fleet_detail = {}
        # The family runs at fam_qps (rates scaled UP by fam_pods/n_pods
        # so a pinned fam_pods fleet saturates — right for comparing
        # routing policies at fixed width, wrong for autoscaling, where
        # the premise is a quiet baseline ONE pod can carry and bursts
        # only the peak fleet can). Dilate arrivals back to the n_pods-
        # calibrated rate — identical request mix and shape, segment
        # durations long relative to the reconcile cadence (the real-
        # world analogue: minutes-long traffic shifts vs a seconds-scale
        # reconcile loop). Both arms see the same schedule.
        dil = fam_pods / n_pods

        def fleet_med(rolls):
            # Per-metric MEDIANS over the BENCH_REPEATS rolls (CPU-smoke
            # wall-clock jitter between identical runs is large; a
            # single draw can eat a 1 s stall in one segment). The last
            # roll's full dict carries the non-judged color (actions,
            # pulls, revived counts); seg tails median element-wise.
            out = dict(rolls[-1])
            for k in (
                "p50_ttft_s", "p90_ttft_s", "p99_ttft_s", "makespan_s",
                "pod_seconds", "prefix_cache_hit_rate", "migration_wall_s",
            ):
                out[k] = round(float(np.median([r[k] for r in rolls])), 4)
            out["migrated"] = int(np.median([r["migrated"] for r in rolls]))
            segs = [r["seg_p99_ttft_s"] for r in rolls]
            out["seg_p99_ttft_s"] = [
                round(float(np.median([s[j] for s in segs])), 4)
                for j in range(len(segs[0]))
            ]
            out["peak_pods"] = max(r["peak_pods"] for r in rolls)
            return out

        for wname, wl in fam_workloads.items():
            wl = [(t * dil, seg, toks) for t, seg, toks in wl]
            static = fleet_med(
                [
                    run_fleet_arm(
                        wl, params, engine_cfg, fam_pods, max_new,
                        dynamic=False,
                    )
                    for _ in range(fam_repeats)
                ]
            )
            dyn = fleet_med(
                [
                    run_fleet_arm(
                        wl, params, engine_cfg, fam_pods, max_new,
                        dynamic=True,
                    )
                    for _ in range(fam_repeats)
                ]
            )
            fleet_detail[wname] = {
                "static_peak": static,
                "controller": dyn,
                "pod_seconds_saved_pct": (
                    round(
                        100.0
                        * (static["pod_seconds"] - dyn["pod_seconds"])
                        / static["pod_seconds"],
                        2,
                    )
                    if static["pod_seconds"]
                    else None
                ),
            }
        # Scale-DOWN drill (the acceptance's "well under DRAIN_TIMEOUT_S,
        # measured in the bench"): start OVER-provisioned (all pods up)
        # with a roomy pool (one pod holds the whole working set, so the
        # aggregate MRC is flat at reduced capacity — `idle_mrc_flat` is
        # the correct call) on a SHORT quiet workload whose decode tails
        # outlive the arrivals. Once traffic ends the controller sheds
        # pods, LIVE-MIGRATING the victims' in-flight decodes;
        # migration_wall_s is the measured freeze/export/import + link
        # time where a drain-based removal waits out DRAIN_TIMEOUT_S
        # (30 s default) per pod. Deliberately NOT a burst arm: with a
        # flat curve the decision table holds on burn (burning_mrc_flat
        # — capacity is not the bottleneck), so bursts would judge the
        # routing regime, not the scale-down path under test here.
        drill_wl = [
            (t * dil, seg, toks) for t, seg, toks in fam_workloads["burst"]
        ][: max(2 * fam_pods, 8)]
        fleet_detail["scaledown_drill"] = fleet_med(
            [
                run_fleet_arm(
                    drill_wl, params, engine_cfg, fam_pods,
                    max(max_new, 32), dynamic=True,
                    start_pods=fam_pods, roomy_pool=True,
                )
                for _ in range(fam_repeats)
            ]
        )

    # -- Tenant QoS arm (ISSUE 18): two classes on one pod ---------------
    # The noisy-neighbor regime the feature exists for: a steady premium
    # trickle over a SMALL hot-prefix set, plus a background tenant
    # running the PR 13 square-wave burst shape over a wide churny
    # prefix set, both against ONE capacity-constrained pod. Three runs:
    # premium alone (the unloaded reference), both classes with the knob
    # off (the background burst wrecks premium's tail and evicts its
    # warm set), and both classes under TENANT_QOS (admission budgets
    # shed background at the door, priority preemption keeps premium's
    # prefill first in line, cache_share keeps its warm set resident).
    tenant_qos_detail = None
    if os.environ.get("BENCH_TENANT_QOS", "0") == "1":
        import dataclasses as _dc

        tq_rng = np.random.default_rng(1812)
        tq_prem_groups = max(n_groups // 4, 2)
        tq_bg_groups = max(n_groups, 4)
        tq_reqs = max(reqs_per_group * 2, 6)
        prem_wl = build_workload(
            tq_rng, tq_prem_groups, tq_reqs, prefix_len, suffix_len,
            model_cfg.vocab_size, [qps_mid * 0.5] * 5,
        )
        bg_wl = build_workload(
            tq_rng, tq_bg_groups, tq_reqs, prefix_len, suffix_len,
            model_cfg.vocab_size,
            [qps_mid * s for s in (0.7, 5.0, 0.7, 5.0, 0.7)],
        )
        merged = sorted(
            [(t, seg, toks, "premium") for t, seg, toks in prem_wl]
            + [(t, seg, toks, "batch") for t, seg, toks in bg_wl],
            key=lambda r: r[0],
        )
        tq_wl = [(t, seg, toks) for t, seg, toks, _name in merged]
        tq_tenants = [name for _t, _seg, _toks, name in merged]
        # Pool sized to hold premium's warm prefix set plus a few active
        # sequences but NOT the background churn — the regime where
        # cache_share has something to protect. (A pool that fits both
        # working sets shows nothing; the main pass already covers it.)
        prefix_pages = -(-prefix_len // page)
        seq_pages = -(-(prefix_len + suffix_len + max_new + 1) // page)
        tq_pages = int(
            os.environ.get(
                "BENCH_TENANT_PAGES",
                str(tq_prem_groups * prefix_pages + 6 * seq_pages),
            )
        )
        tq_cfg = _dc.replace(
            engine_cfg,
            block_manager=_dc.replace(
                engine_cfg.block_manager, total_pages=tq_pages
            ),
        )
        tq_spec = os.environ.get(
            "BENCH_TENANT_QOS_SPEC",
            "premium:prio=0,weight=4;"
            "batch:prio=1,max_waiting=6,cache_share=0.3",
        )
        prem_only = [r for r, t in zip(tq_wl, tq_tenants) if t == "premium"]
        tenant_qos_detail = {
            "total_pages": tq_pages,
            "qos_spec": tq_spec,
            "n_premium": len(prem_only),
            "n_background": len(tq_wl) - len(prem_only),
            "unloaded_premium": run_tenant_qos_arm(
                prem_only, lambda _i: "premium", params, tq_cfg, max_new
            ),
            "knob_off": run_tenant_qos_arm(
                tq_wl, lambda i: tq_tenants[i], params, tq_cfg, max_new
            ),
            "knob_on": run_tenant_qos_arm(
                tq_wl, lambda i: tq_tenants[i], params, tq_cfg, max_new,
                qos_spec=tq_spec,
            ),
        }

    # -- KV integrity arm (ISSUE 19): corruption drill + overhead A/B ----
    # Three runs of one spill-heavy workload on one pod: knob off (the
    # baseline greedy outputs), KV_INTEGRITY on clean (what the digests
    # cost when nothing is wrong — the knob's price tag), and KV_INTEGRITY
    # on with byte flips injected into spilled host pages (the drill:
    # every flip detected + quarantined before any token, recovery by
    # cold recompute to EXACT output parity with the baseline).
    kv_integrity_detail = None
    if os.environ.get("BENCH_KV_INTEGRITY", "0") == "1":
        import dataclasses as _dc

        ki_rng = np.random.default_rng(1907)
        ki_groups = max(n_groups // 2, 4)
        ki_wl = build_workload(
            ki_rng, ki_groups, max(reqs_per_group, 3), prefix_len,
            suffix_len, model_cfg.vocab_size, [qps_mid] * 3,
        )
        prefix_pages = -(-prefix_len // page)
        seq_pages = -(-(prefix_len + suffix_len + max_new + 1) // page)
        # Pool holds ~2 active sequences; the host tier holds the whole
        # prefix working set with slack — every revisit restores from
        # host, so the verify-on-transition path carries the run.
        ki_pages = int(
            os.environ.get(
                "BENCH_KV_INTEGRITY_PAGES", str(2 * seq_pages + 2)
            )
        )
        ki_host = ki_groups * (prefix_pages + seq_pages) * 2
        ki_flips = int(os.environ.get("BENCH_KV_INTEGRITY_FLIPS", "4"))

        def ki_cfg(knob):
            return _dc.replace(
                engine_cfg,
                kv_integrity=knob,
                host_tier_policy="always",
                block_manager=_dc.replace(
                    engine_cfg.block_manager,
                    total_pages=ki_pages,
                    host_pages=ki_host,
                ),
            )

        # Throwaway prelude: a tiny knob-off pass (with one revisit, so
        # the spill→restore path runs) absorbs the process-level
        # one-time costs of this pool shape — trace/dispatch of the
        # cold-prefill, warm-prefill, and restore paths — which would
        # otherwise land entirely in the FIRST timed run and skew the
        # overhead A/B.
        run_kv_integrity_arm(
            ki_wl[:3] + ki_wl[:1], params, ki_cfg(False), max_new
        )
        ki_off, ki_off_out = run_kv_integrity_arm(
            ki_wl, params, ki_cfg(False), max_new
        )
        ki_clean, ki_clean_out = run_kv_integrity_arm(
            ki_wl, params, ki_cfg(True), max_new
        )
        ki_drill, ki_drill_out = run_kv_integrity_arm(
            ki_wl, params, ki_cfg(True), max_new,
            flips=ki_flips, flip_seed=1907,
        )
        kv_integrity_detail = {
            "total_pages": ki_pages,
            "host_pages": ki_host,
            "n_requests": len(ki_wl),
            "off": ki_off,
            "on_clean": ki_clean,
            "on_drill": ki_drill,
            # The zero-corrupted-tokens bars: clean knob-on must be
            # bit-identical to knob-off, and the drill — with every
            # injected flip detected and recomputed — must be too.
            "clean_parity_ok": bool(ki_clean_out == ki_off_out),
            "drill_parity_ok": bool(ki_drill_out == ki_off_out),
            "overhead_makespan_x": (
                round(ki_clean["makespan_s"] / ki_off["makespan_s"], 3)
                if ki_off["makespan_s"]
                else None
            ),
            # Median per-request latency is the sturdier overhead stat at
            # smoke sizes — makespan is a sum of ~ms steps and CPU jitter
            # swamps a crc32's worth of signal.
            "overhead_p50_x": (
                round(ki_clean["p50_request_s"] / ki_off["p50_request_s"], 3)
                if ki_off["p50_request_s"]
                else None
            ),
            "drill_over_clean_x": (
                round(ki_drill["makespan_s"] / ki_clean["makespan_s"], 3)
                if ki_clean["makespan_s"]
                else None
            ),
        }

    # -- Fleet-federation arm (ISSUE 20): scrape/join overhead A/B --------
    # Headline: 4-pod FleetFederator.scrape() join latency against fully
    # loaded in-process payloads. A/B: engine step p50 with a ~10 Hz
    # background scraper reading LIVE engine state vs the bare engine —
    # the observation plane must not tax the hot path (<= 2%).
    obs_fed_detail = None
    if os.environ.get("BENCH_OBS_FED", "0") == "1":
        obs_fed_detail = obs_fed_overhead_ab(
            params, engine_cfg, workload, max_new
        )

    # Headline metrics are precise-vs-round_robin by definition: when a
    # BENCH_POLICIES subset omits either, the corresponding fields are
    # null rather than silently reporting another policy's numbers.
    precise = results.get("precise")
    rr = results.get("round_robin")
    reduction = None
    if precise is not None and rr is not None and rr["p50_ttft_s"] > 0:
        reduction = (
            100.0
            * (rr["p50_ttft_s"] - precise["p50_ttft_s"])
            / rr["p50_ttft_s"]
        )

    detail = {
        "backend": jax.default_backend(),
        "smoke": smoke,
        "model": model_label,  # the config branch actually taken
        "quantize": quantize,
        "n_pods": n_pods,
        "n_groups": n_groups,
        "n_requests": len(workload),
        "prefix_len": prefix_len,
        "host_pages": host_pages,
        "total_pages": total_pages,
        "chunked_prefill_tokens": chunked if chunked > 0 else None,
        "decode_fastpath": decode_fastpath,
        "spec_decode": spec_mode,
        "step_phases": STEP_PHASES,
        "transfer": os.environ.get("BENCH_TRANSFER", "0") == "1",
        "remote_tier": os.environ.get("BENCH_REMOTE_TIER", "0") == "1",
        "event_lag_ms": float(os.environ.get("BENCH_EVENT_LAG_MS", "2")),
        "qps_ramp": [round(q, 2) for q in qps_ramp],
        # Host-arm knobs are reported only when a host-tier arm actually
        # ran; otherwise a default run would record knob defaults for
        # arms that never executed.
        "kv_quant": kv_quant if (host_pages or pressure_host_pages) else None,
        "host_prefetch": (
            host_prefetch if (host_pages or pressure_host_pages) else None
        ),
        "host_tier_policy": (
            host_tier_policy if (host_pages or pressure_host_pages) else None
        ),
        "results": results,
        "pressure_total_pages": pressure_pages,
        "pressure_host_pages": pressure_host_pages,
        "pressure_results": pressure_results,
        "lifecycle_overhead_ab": overhead_ab,
        "disagg": disagg_result,
        "workload_family": family_results,
        "workload_family_spread": family_spreads,
        "fleet_controller": fleet_detail,
        "tenant_qos": tenant_qos_detail,
        "kv_integrity": kv_integrity_detail,
        "obs_fed": obs_fed_detail,
    }
    print(json.dumps(detail), file=sys.stderr)

    pressure = None
    if pressure_results:
        import statistics

        pressure = {"total_pages": pressure_pages}
        for pol, res in pressure_results.items():
            # MEDIANS over the BENCH_REPEATS rounds for every TTFT/ITL
            # percentile field, not just the hit rate (single round =
            # the legacy single-shot field, value for value).
            lat = pressure_lat.get(pol, {})

            def med(key, fallback=None):
                vals = lat.get(key) or (
                    [res[key]] if res.get(key) is not None else []
                )
                return round(statistics.median(vals), 4) if vals else fallback

            pressure[f"p50_{pol}"] = med("p50_ttft_s")
            pressure[f"p90_{pol}"] = med("p90_ttft_s")
            pressure[f"p99_{pol}"] = med("p99_ttft_s")
            pressure[f"itl_p90_{pol}"] = med("p90_itl_s")
            hits = pressure_hits.get(pol) or [res["prefix_cache_hit_rate"]]
            pressure[f"hit_{pol}"] = round(statistics.median(hits), 4)
        if any(len(h) > 1 for h in pressure_hits.values()):
            pressure["hit_spread"] = {
                pol: {
                    "rounds": len(h),
                    "min": round(min(h), 4),
                    "max": round(max(h), 4),
                }
                for pol, h in pressure_hits.items()
                if len(h) > 1
            }
        if any(
            len(vals) > 1
            for lat in pressure_lat.values()
            for vals in lat.values()
        ):
            pressure["latency_spread"] = {
                pol: {
                    k: {
                        "rounds": len(vals),
                        "min": round(min(vals), 4),
                        "max": round(max(vals), 4),
                    }
                    for k, vals in lat.items()
                    if len(vals) > 1
                }
                for pol, lat in pressure_lat.items()
                if any(len(v) > 1 for v in lat.values())
            }
        pe, pp = (
            pressure_results.get("estimated"),
            pressure_results.get("precise"),
        )
        if pe and pp and pp["p90_ttft_s"] > 0:
            # The eviction-awareness headline: how much worse the
            # index-free router's tail is once pods evict. With
            # BENCH_REPEATS > 1 the reported ratio is the MEDIAN over the
            # repeated races and a spread field carries the min/max, so
            # CPU-jitter rounds stop masquerading as signal.
            if len(pressure_race_ratios) > 1:
                import statistics

                pressure["p90_estimated_over_precise"] = round(
                    statistics.median(pressure_race_ratios), 3
                )
                pressure["p90_estimated_over_precise_spread"] = {
                    "rounds": len(pressure_race_ratios),
                    "min": round(min(pressure_race_ratios), 3),
                    "max": round(max(pressure_race_ratios), 3),
                }
            else:
                pressure["p90_estimated_over_precise"] = round(
                    pe["p90_ttft_s"] / pp["p90_ttft_s"], 3
                )
        if pp and "audit" in pp:
            # The forced-eviction regime's audit columns: pool pressure
            # makes pods evict between scoring and serving, so this is
            # where the miss attribution proves itself.
            pressure["audit_precise"] = pp["audit"]
            pressure["staleness_precise"] = pp.get("staleness")
        ph = pressure_results.get("precise_host")
        if ph is not None:
            # The capacity headline (ISSUE 6): host tier + int8 KV spill
            # under pressure, vs the UNPRESSURED precise arm (target:
            # p50 within 2x, hit rate back above 0.8).
            pressure["host_pages"] = pressure_host_pages
            pressure["kv_quant"] = kv_quant
            if precise is not None and precise["p50_ttft_s"] > 0:
                pressure["p50_host_over_unpressured_precise"] = round(
                    ph["p50_ttft_s"] / precise["p50_ttft_s"], 3
                )
        # MRC validation (ISSUE 15 acceptance): the reuse-distance curve's
        # predicted hit rate at each TIER arm's configured cumulative
        # capacity must sit within 0.05 of the measured pressure-arm hit
        # rate — medians over the repeat rounds on both sides. The
        # bare-HBM point of the same curve is recorded as an honest
        # diagnostic, NOT an acceptance row: under churn the pool is not
        # a clean LRU (ref-pinned active pages + decode growth shrink the
        # effective capacity below the page count), so the curve
        # overpredicts there — the TIER-sizing delta (what host/remote
        # capacity adds on top) is exactly where the model is exact.
        def _mrc_point(arm, capname):
            res_arm = pressure_results.get(arm)
            if res_arm is None or "mrc" not in res_arm:
                return None
            preds = pressure_mrc.get(arm, {}).get(capname) or []
            measured = pressure.get(f"hit_{arm}")
            if not preds or measured is None:
                return None
            predicted = round(statistics.median(preds), 4)
            return {
                "capacity_blocks": res_arm["mrc"]["capacities"][capname],
                "predicted_hit": predicted,
                "measured_hit": measured,
                "abs_error": round(abs(predicted - measured), 4),
                "ok": bool(abs(predicted - measured) <= 0.05),
                "cold_fraction": res_arm["mrc"]["cold_fraction"],
            }

        mrc_val = {}
        for arm, capname in (
            ("precise_host", "hbm_host"),
            ("precise_remote", "hbm_fleet_share"),
        ):
            point = _mrc_point(arm, capname)
            if point is not None:
                mrc_val[arm] = point
        if mrc_val:
            pressure["mrc_validation"] = mrc_val
            hbm_point = _mrc_point("precise", "hbm")
            if hbm_point is not None:
                hbm_point.pop("ok", None)  # diagnostic, not a bar
                pressure["mrc_hbm_point"] = hbm_point
        prm = pressure_results.get("precise_remote")
        if prm is not None:
            # The fleet-pool headline (ISSUE 13): eviction-as-demotion
            # under pressure. Acceptance: median hit back >= 0.8 (vs the
            # 0.533 host-tier ceiling), pressure-arm evicted_on_pod
            # attributed misses ~ 0, and the effective-capacity number
            # (fleet tokens cached / HBM bytes) no single-pod tier holds.
            pressure["remote"] = {
                k: prm["remote"][k]
                for k in (
                    "store_pages",
                    "store_cached",
                    "demoted_blocks",
                    "demote_wire_bytes",
                    "remote_pulls",
                    "remote_pulled_blocks",
                    "fleet_cached_tokens",
                    "hbm_bytes",
                    "effective_capacity_x_hbm",
                    "tokens_per_hbm_gib",
                )
            }
            pressure["audit_precise_remote"] = prm.get("audit")
            if precise is not None and precise["p50_ttft_s"] > 0:
                pressure["p50_remote_over_unpressured_precise"] = round(
                    prm["p50_ttft_s"] / precise["p50_ttft_s"], 3
                )
        pq = pressure_results.get("precise_hbm_q8")
        if pq is not None and pp is not None:
            # The quantized-HBM headline (ISSUE 16): same HBM bytes, 2x
            # the pages. Forecast-vs-measured closes the pre-registration
            # loop (the predicted number was recorded from the bare arm's
            # curve BEFORE the kernel landed); the throughput A/B and the
            # per-phase deltas show what in-kernel dequant costs (or
            # saves — decode is DMA-bound) on the same workload.
            preds_2x = pressure_mrc.get("precise", {}).get("hbm_2x") or []
            measured = pressure.get("hit_precise_hbm_q8")
            hbm_q8 = {
                "kv_quant_hbm": "int8",
                "total_pages_2x": 2 * pressure_pages,
                "measured_hit": measured,
            }
            if preds_2x and measured is not None:
                predicted = round(statistics.median(preds_2x), 4)
                hbm_q8["mrc_predicted_hit_2x"] = predicted
                hbm_q8["abs_error"] = round(abs(predicted - measured), 4)
                hbm_q8["ok"] = bool(abs(predicted - measured) <= 0.05)
            if pp["output_tok_s_per_chip"] > 0:
                hbm_q8["tok_s_per_chip"] = {
                    "precise": round(pp["output_tok_s_per_chip"], 3),
                    "precise_hbm_q8": round(pq["output_tok_s_per_chip"], 3),
                    "ratio": round(
                        pq["output_tok_s_per_chip"]
                        / pp["output_tok_s_per_chip"],
                        3,
                    ),
                }
            if "phases" in pp and "phases" in pq:
                hbm_q8["phase_deltas"] = {
                    key: {
                        "precise_s": pp["phases"].get(key, 0),
                        "precise_hbm_q8_s": pq["phases"].get(key, 0),
                        "delta_s": round(
                            pq["phases"].get(key, 0)
                            - pp["phases"].get(key, 0),
                            4,
                        ),
                    }
                    for key in ("decode_s", "sample_s")
                }
            pressure["kv_quant_hbm"] = hbm_q8

    # Workload-family headline (ISSUE 14): per-arm p50/p99 TTFT for the
    # three policies, the burst+ramp acceptance verdicts (predicted must
    # beat BOTH comparators on both tails, medians over BENCH_REPEATS,
    # with hit parity vs precise), and the latency model's honesty
    # (median realized/predicted TTFT over the predicted arms' joins).
    fam_headline = None
    if family_results:
        import statistics as _stats

        fam_acceptance = {}
        for arm in ("burst", "ramp"):
            per = family_results.get(arm, {})
            pred, rr_, prec = (
                per.get("predicted"), per.get("round_robin"),
                per.get("precise"),
            )
            if not (pred and rr_ and prec):
                continue
            fam_acceptance[arm] = {
                "p50_ok": bool(
                    pred["p50_ttft_s"] <= rr_["p50_ttft_s"]
                    and pred["p50_ttft_s"] <= prec["p50_ttft_s"]
                ),
                "p99_ok": bool(
                    pred["p99_ttft_s"] <= rr_["p99_ttft_s"]
                    and pred["p99_ttft_s"] <= prec["p99_ttft_s"]
                ),
                "hit_parity_ok": bool(
                    pred["prefix_cache_hit_rate"]
                    >= prec["prefix_cache_hit_rate"] - 0.02
                ),
            }
        ttft_ratios = [
            per["predicted"]["audit"]["ttft_ratio_p50"]
            for per in family_results.values()
            if per.get("predicted", {}).get("audit", {}).get("ttft_ratio_p50")
            is not None
        ]
        fam_headline = {
            "repeats": fam_repeats,
            "arms": {
                wname: {
                    pol: {
                        "p50_ttft_s": round(res["p50_ttft_s"], 4),
                        "p99_ttft_s": round(res["p99_ttft_s"], 4),
                        "hit": round(res["prefix_cache_hit_rate"], 4),
                    }
                    for pol, res in per_pol.items()
                }
                for wname, per_pol in family_results.items()
            },
            "acceptance": fam_acceptance,
            "ttft_ratio_p50": (
                round(float(_stats.median(ttft_ratios)), 4)
                if ttft_ratios
                else None
            ),
        }
    print(
        json.dumps(
            {
                "metric": "p50_ttft_reduction_vs_round_robin",
                "value": round(reduction, 2) if reduction is not None else None,
                "unit": "%",
                "vs_baseline": (
                    round(reduction / 50.0, 4) if reduction is not None else None
                ),
                "req_s_per_chip": (
                    round(precise["req_s_per_chip"], 3) if precise else None
                ),
                "prefix_cache_hit_rate": (
                    round(precise["prefix_cache_hit_rate"], 4) if precise else None
                ),
                "output_tok_s_per_chip": (
                    round(precise["output_tok_s_per_chip"], 1) if precise else None
                ),
                "decode_fastpath": decode_fastpath,
                # Spec-decode arm headline: acceptance rate + throughput
                # (null unless BENCH_SPEC_DECODE ran the arm).
                "spec": (
                    {
                        "mode": spec_mode,
                        "acceptance_rate": results["precise_spec"]["spec"][
                            "acceptance_rate"
                        ],
                        "output_tok_s_per_chip": round(
                            results["precise_spec"]["output_tok_s_per_chip"], 1
                        ),
                    }
                    if "precise_spec" in results
                    else None
                ),
                # Serving-SLO latency columns (precise policy): the perf
                # trajectory tracks tails, not just medians/throughput.
                "latency": (
                    {
                        k: (round(precise[k], 4) if precise[k] is not None else None)
                        for k in (
                            "p50_ttft_s", "p90_ttft_s", "p99_ttft_s",
                            "p50_itl_s", "p90_itl_s", "p99_itl_s",
                        )
                    }
                    if precise
                    else None
                ),
                # Routing-quality audit columns (ISSUE 10; precise arm):
                # event-plane staleness percentiles + the realized share
                # of predicted warmth with attributed misses.
                "routing_audit": (
                    {
                        "staleness_p50_ms": precise["staleness"]["p50_ms"],
                        "staleness_p99_ms": precise["staleness"]["p99_ms"],
                        "realized_over_predicted": precise["audit"][
                            "realized_over_predicted"
                        ],
                        "misses": precise["audit"]["misses"],
                    }
                    if precise and "audit" in precise and "staleness" in precise
                    else None
                ),
                "pressure": pressure,
                # Lifecycle/flight overhead A/B (ISSUE 15): knobs-on
                # engine-step p50 over knobs-off (bar: within 2%).
                "lifecycle_overhead_ab": overhead_ab,
                # Disagg arm headline (null unless BENCH_DISAGG ran): the
                # decode-tier ITL isolation win over the same-size mixed
                # fleet, and the two-hop placement/handoff accounting.
                "disagg": (
                    {
                        "n_prefill": disagg_result["n_prefill"],
                        "n_decode": disagg_result["n_decode"],
                        "p90_itl_s": (
                            round(disagg_result["p90_itl_s"], 4)
                            if disagg_result["p90_itl_s"] is not None
                            else None
                        ),
                        "p50_ttft_s": round(disagg_result["p50_ttft_s"], 4),
                        "handoffs": disagg_result["handoffs"],
                        "p90_itl_mixed_over_disagg": (
                            round(
                                precise["p90_itl_s"]
                                / disagg_result["p90_itl_s"],
                                3,
                            )
                            if precise is not None
                            and precise.get("p90_itl_s")
                            and disagg_result["p90_itl_s"]
                            else None
                        ),
                    }
                    if disagg_result is not None
                    else None
                ),
                # Predicted-TTFT routing headline (ISSUE 14; null unless
                # the workload-family pass ran): per-arm tails, the
                # burst+ramp acceptance verdicts, and the latency
                # model's realized/predicted honesty median.
                "workload_family": fam_headline,
                # Fleet-controller headline (ISSUE 17; null unless the
                # BENCH_FLEET pass ran): per-shape controller-vs-static-
                # peak pod-seconds and p99 TTFT, plus the controller's
                # action log sizes — the autoscaling verdict columns.
                "fleet_controller": (
                    {
                        wname: {
                            # The scale-down drill is a single dynamic
                            # arm (no static comparator): its verdict
                            # columns are the shed/migration measurements.
                            "scale_actions": len(row.get("actions", [])),
                            "pods_shed": sum(
                                1
                                for a in row.get("actions", [])
                                if a["action"] == "scale_down"
                            ),
                            "migrated": row["migrated"],
                            "migration_wall_s": row["migration_wall_s"],
                            "p99_ttft_s": round(row["p99_ttft_s"], 4),
                            "pod_seconds": row["pod_seconds"],
                        }
                        if "static_peak" not in row
                        else {
                            "static_p99_ttft_s": round(
                                row["static_peak"]["p99_ttft_s"], 4
                            ),
                            "controller_p99_ttft_s": round(
                                row["controller"]["p99_ttft_s"], 4
                            ),
                            "static_pod_seconds": row["static_peak"][
                                "pod_seconds"
                            ],
                            "controller_pod_seconds": row["controller"][
                                "pod_seconds"
                            ],
                            "pod_seconds_saved_pct": row[
                                "pod_seconds_saved_pct"
                            ],
                            "peak_pods": row["controller"]["peak_pods"],
                            "scale_actions": len(
                                row["controller"].get("actions", [])
                            ),
                            "migrated": row["controller"]["migrated"],
                            "revived_blocks": row["controller"][
                                "revived_blocks"
                            ],
                        }
                        for wname, row in fleet_detail.items()
                    }
                    if fleet_detail
                    else None
                ),
                # Tenant-QoS headline (ISSUE 18; null unless the
                # BENCH_TENANT_QOS pass ran): premium's tail with the
                # knob off vs on vs unloaded, its hit-rate protection,
                # and the background degradation mix (429s at the door +
                # priority preemptions — never errors).
                "tenant_qos": (
                    {
                        "premium_p99_unloaded_s": tenant_qos_detail[
                            "unloaded_premium"
                        ]["tenants"]["premium"]["p99_ttft_s"],
                        "premium_p99_off_s": tenant_qos_detail["knob_off"][
                            "tenants"
                        ]["premium"]["p99_ttft_s"],
                        "premium_p99_on_s": tenant_qos_detail["knob_on"][
                            "tenants"
                        ]["premium"]["p99_ttft_s"],
                        "premium_hit_unloaded": tenant_qos_detail[
                            "unloaded_premium"
                        ]["tenants"]["premium"]["prefix_cache_hit_rate"],
                        "premium_hit_off": tenant_qos_detail["knob_off"][
                            "tenants"
                        ]["premium"]["prefix_cache_hit_rate"],
                        "premium_hit_on": tenant_qos_detail["knob_on"][
                            "tenants"
                        ]["premium"]["prefix_cache_hit_rate"],
                        "background_rejected": tenant_qos_detail["knob_on"][
                            "tenants"
                        ]["batch"]["rejected"],
                        "priority_preempted": tenant_qos_detail["knob_on"][
                            "priority_preempted"
                        ],
                    }
                    if tenant_qos_detail
                    else None
                ),
                # KV-integrity headline (ISSUE 19; null unless the
                # BENCH_KV_INTEGRITY pass ran): detection completeness
                # for the injected flips, both parity bars (zero
                # corrupted tokens), and the two makespan price tags —
                # the digests when nothing is wrong, the recovery when
                # something is.
                "kv_integrity": (
                    {
                        "injected_flips": kv_integrity_detail["on_drill"][
                            "injected_flips"
                        ],
                        "detected": kv_integrity_detail["on_drill"][
                            "integrity"
                        ]["checks_corrupt"],
                        "quarantined": kv_integrity_detail["on_drill"][
                            "integrity"
                        ]["quarantined"],
                        "clean_parity_ok": kv_integrity_detail[
                            "clean_parity_ok"
                        ],
                        "drill_parity_ok": kv_integrity_detail[
                            "drill_parity_ok"
                        ],
                        "overhead_makespan_x": kv_integrity_detail[
                            "overhead_makespan_x"
                        ],
                        "overhead_p50_x": kv_integrity_detail[
                            "overhead_p50_x"
                        ],
                        "drill_over_clean_x": kv_integrity_detail[
                            "drill_over_clean_x"
                        ],
                    }
                    if kv_integrity_detail
                    else None
                ),
                # Fleet-federation headline (ISSUE 20; null unless the
                # BENCH_OBS_FED pass ran): the 4-pod snapshot join
                # latency (p50/p99) and the step-p50 price of a live
                # federator scraping the engine at ~100 Hz mid-decode.
                "obs_fed": (
                    {
                        "join_pods": obs_fed_detail["join_pods"],
                        "join_p50_s": obs_fed_detail["join_p50_s"],
                        "join_p99_s": obs_fed_detail["join_p99_s"],
                        "scrapes_during_on": obs_fed_detail[
                            "scrapes_during_on"
                        ],
                        "step_p50_on_over_off": obs_fed_detail[
                            "p50_on_over_off"
                        ],
                    }
                    if obs_fed_detail
                    else None
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
