"""Routing-quality observability suite (ISSUE 10 acceptance).

The audit plane closes the loop between prediction and reality:

- **Staleness probes**: publish→index-visibility lag per (pod, event
  type) from the EventBatch timestamps the wire already carries, plus a
  per-pod events-behind gauge from the subscriber seq numbers.
- **Route audit**: the router records predicted matched blocks + the
  scoreboard per request id; the pod reports realized prefix-cache hits
  via a trailing-append ``RequestAudit`` KV event; the ``RouteAuditor``
  joins them (ratio, regret, bounded ring at ``/debug/audit``).
- **Miss attribution**: realized < predicted is classified with current
  index + fleet-health state: ``stale_index`` / ``evicted_on_pod`` /
  ``never_stored`` / ``dead_pod_reroute``.
- **SLO burn-rate recording**: ``OBS_SLO`` objectives evaluated
  in-process over sliding windows.
- **Knobs-off parity** (the hard contract): with ``OBS_AUDIT``/``OBS_SLO``
  unset — response keys, ``/stats`` key sets, heartbeat + transfer +
  KV-event wire bytes, and the pod's published event stream are
  bit-identical legacy.
- **Fleet acceptance**: a 2-pod in-process fleet joins predicted ==
  realized on a warm route end to end (real engines, real event wire),
  and a forced eviction after scoring attributes ``stale_index``.
"""

import asyncio
import time

import msgpack
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from chaos import ChaosLink
from llm_d_kv_cache_manager_tpu.kvcache import (
    BlendedRouter,
    KVCacheIndexer,
    KVCacheIndexerConfig,
    PrefixAffinityTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    BlockRemoved,
    EventBatch,
    FleetHealth,
    FleetHealthConfig,
    Heartbeat,
    KVEventsPool,
    KVEventsPoolConfig,
    Message,
    RequestAudit,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvcache.transfer import encode_request
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.obs.audit import (
    RouteAuditor,
    StalenessTracker,
    debug_audit_payload,
    debug_staleness_payload,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (
    SLORecorder,
    parse_slo_spec,
    parse_windows,
)
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

PS = 4
MODEL = "tiny-llama"


def _engine_config(total_pages=64):
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=PS),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
    )


def _pod_config(pod_id, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=kw.pop("publish_events", False),
        engine=_engine_config(total_pages=kw.pop("total_pages", 64)),
        **kw,
    )


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _keys(hashes, model=MODEL):
    return [Key(model_name=model, chunk_hash=h) for h in hashes]


def _entries(pods):
    return [PodEntry(pod_identifier=p, device_tier="tpu_hbm") for p in pods]


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


class TestRequestAuditWire:
    def test_round_trip(self):
        payload = EventBatch(
            ts=1.5, events=[RequestAudit(request_id="r-1", realized_blocks=7)]
        ).to_payload()
        batch = decode_event_batch(payload)
        (ev,) = batch.events
        assert isinstance(ev, RequestAudit)
        assert ev.request_id == "r-1" and ev.realized_blocks == 7

    def test_wire_bytes_are_trailing_append(self):
        payload = EventBatch(
            ts=1.0, events=[RequestAudit("rid", 3)]
        ).to_payload()
        assert payload == msgpack.packb(
            [1.0, [["RequestAudit", "rid", 3]]], use_bin_type=True
        )

    def test_malformed_fields_tolerated(self):
        raw = msgpack.packb([1.0, [["RequestAudit", 42, "x"]]], use_bin_type=True)
        (ev,) = decode_event_batch(raw).events
        assert ev.request_id == "" and ev.realized_blocks == 0

    def test_legacy_event_bytes_unchanged(self):
        """The PR adds a NEW tag; every pre-existing event's bytes are
        untouched (heartbeat + KV-event wire parity pin)."""
        assert EventBatch(
            ts=1.0, events=[Heartbeat(dropped_batches=3)]
        ).to_payload() == msgpack.packb(
            [1.0, [["Heartbeat", 3]]], use_bin_type=True
        )
        assert EventBatch(
            ts=1.0, events=[BlockRemoved(block_hashes=[5])]
        ).to_payload() == msgpack.packb(
            [1.0, [["BlockRemoved", [5], None]]], use_bin_type=True
        )

    def test_transfer_request_bytes_unchanged(self):
        assert encode_request("m", [1, 2], 8) == msgpack.packb(
            ["FetchBlocks", "m", [1, 2], 8], use_bin_type=True
        )


# ---------------------------------------------------------------------------
# StalenessTracker
# ---------------------------------------------------------------------------


class TestStalenessTracker:
    def test_lag_recorded_per_pod_and_event(self):
        now = [100.0]
        t = StalenessTracker(clock=lambda: now[0])
        t.observe_batch("pa", 1, 99.9, ["BlockStored", "BlockStored"])
        t.observe_batch("pb", 1, 99.0, ["Heartbeat"])
        snap = t.snapshot()
        assert snap["events_observed"] == 3
        assert abs(snap["max_lag_s"] - 1.0) < 1e-9
        d = t.detail()
        assert d["per_pod_event"]["pa/BlockStored"]["count"] == 2
        assert d["per_pod_event"]["pb/Heartbeat"]["count"] == 1

    def test_zero_ts_records_nothing(self):
        t = StalenessTracker(clock=lambda: 100.0)
        t.observe_batch("pa", 1, 0.0, ["BlockStored"])
        assert t.snapshot()["events_observed"] == 0

    def test_clock_skew_clamps_to_zero(self):
        t = StalenessTracker(clock=lambda: 100.0)
        t.observe_batch("pa", 1, 100.5, ["BlockStored"])  # publisher ahead
        assert t.snapshot()["max_lag_s"] == 0.0

    def test_events_behind_from_seq_high_waters(self):
        t = StalenessTracker(clock=lambda: 0.0)
        t.observe_received("pa", 5)
        t.observe_received("pa", 9)
        t.observe_batch("pa", 7, 0.0, [])
        assert t.events_behind() == {"pa": 2}
        t.observe_batch("pa", 9, 0.0, [])
        assert t.events_behind() == {"pa": 0}

    def test_events_behind_counts_enqueued_before_first_apply(self):
        # Cold-start storm: the subscriber enqueues a burst the shard
        # worker hasn't touched — the gauge must read the backlog, not 0
        # (the applied high-water seeds one below the first seq seen).
        t = StalenessTracker(clock=lambda: 0.0)
        t.observe_received("pa", 0)
        t.observe_received("pa", 4)
        assert t.events_behind() == {"pa": 5}
        t.observe_batch("pa", 4, 0.0, [])
        assert t.events_behind() == {"pa": 0}

    def test_percentiles(self):
        now = [10.0]
        t = StalenessTracker(clock=lambda: now[0])
        for lag in (0.01, 0.02, 0.03, 0.04, 1.0):
            t.observe_batch("pa", 1, now[0] - lag, ["BlockStored"])
        p = t.percentiles()
        assert 0.02 <= p["p50"] <= 0.04
        assert p["p99"] == 1.0

    def test_pool_integration_observes_wire_batches(self):
        idx = InMemoryIndex()
        now = [50.0]
        tracker = StalenessTracker(clock=lambda: now[0])
        pool = KVEventsPool(
            idx, KVEventsPoolConfig(concurrency=1), staleness=tracker
        )
        pool.start()
        try:
            from llm_d_kv_cache_manager_tpu.kvcache.kvevents import BlockStored

            payload = EventBatch(
                ts=49.9,
                events=[BlockStored(block_hashes=[1, 2], block_size=PS)],
            ).to_payload()
            pool.add_task(
                Message(
                    topic=f"kv@pa@{MODEL}",
                    pod_identifier="pa",
                    model_name=MODEL,
                    payload=payload,
                    seq=3,
                )
            )
            assert pool.drain(timeout=5.0)
        finally:
            pool.shutdown()
        snap = tracker.snapshot()
        assert snap["events_observed"] == 1
        assert abs(snap["max_lag_s"] - 0.1) < 1e-6
        assert tracker.events_behind() == {"pa": 0}
        # The index itself saw the blocks — observation never filters.
        assert idx.lookup(_keys([1, 2]), None)

    def test_unattached_pool_has_no_tracker(self):
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=1))
        assert pool.staleness is None and pool.audit is None


# ---------------------------------------------------------------------------
# RouteAuditor
# ---------------------------------------------------------------------------


class TestRouteAuditor:
    def test_exact_prediction_joins_with_ratio_one_and_no_cause(self):
        a = RouteAuditor()
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4,
            scoreboard={"pa": 4, "pb": 2},
        )
        rec = a.record_realized("r1", "pa", 4)
        assert rec.ratio == 1.0 and rec.cause is None
        assert rec.regret_blocks == 0
        snap = a.snapshot()
        assert snap["joined"] == 1 and snap["pending"] == 0
        assert all(v == 0 for v in snap["miss_causes"].values())

    def test_regret_is_best_minus_chosen(self):
        a = RouteAuditor()
        a.record_decision(
            "r1", chosen_pod="pb", predicted_blocks=2,
            scoreboard={"pa": 6, "pb": 2}, decision="cold",
        )
        rec = a.record_realized("r1", "pb", 2)
        assert rec.regret_blocks == 4 and rec.decision == "cold"

    def test_unmatched_realized_counted(self):
        a = RouteAuditor()
        assert a.record_realized("nope", "pa", 1) is None
        assert a.snapshot()["unmatched_realized"] == 1

    def test_pending_cap_evicts_oldest(self):
        a = RouteAuditor(pending_cap=2)
        for i in range(3):
            a.record_decision(
                f"r{i}", chosen_pod="pa", predicted_blocks=1,
                scoreboard={"pa": 1},
            )
        assert a.snapshot()["pending"] == 2
        assert a.snapshot()["pending_evicted"] == 1
        assert a.record_realized("r0", "pa", 1) is None  # evicted

    def test_ring_is_bounded(self):
        a = RouteAuditor(ring=2)
        for i in range(5):
            a.record_decision(
                f"r{i}", chosen_pod="pa", predicted_blocks=1,
                scoreboard={"pa": 1},
            )
            a.record_realized(f"r{i}", "pa", 1)
        assert len(a.recent(limit=10)) == 2

    # -- miss attribution ----------------------------------------------------
    def _warm_index(self, hashes, pod="pa"):
        idx = InMemoryIndex()
        idx.add(_keys(hashes), _entries([pod]))
        return idx

    def test_attribution_dead_pod_reroute_on_pod_mismatch(self):
        a = RouteAuditor()
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4, scoreboard={"pa": 4}
        )
        rec = a.record_realized("r1", "pb", 0)
        assert rec.cause == "dead_pod_reroute"

    def test_attribution_dead_pod_reroute_on_unroutable_pod(self):
        fh = FleetHealth(FleetHealthConfig())
        fh.observe_drained("pa")
        a = RouteAuditor(fleet_health=fh)
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4, scoreboard={"pa": 4}
        )
        rec = a.record_realized("r1", "pa", 0)
        assert rec.cause == "dead_pod_reroute"

    def test_attribution_never_stored_when_index_never_claimed(self):
        a = RouteAuditor(index=InMemoryIndex())
        # Prediction came from affinity memory: index_blocks=0.
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4, index_blocks=0,
            scoreboard={}, chain_hashes=(1, 2, 3, 4),
        )
        rec = a.record_realized("r1", "pa", 0)
        assert rec.cause == "never_stored"

    def test_attribution_stale_index_when_entries_evicted_after_scoring(self):
        hashes = (1, 2, 3, 4)
        idx = self._warm_index(hashes)
        a = RouteAuditor(index=idx, model_name=MODEL)
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4,
            scoreboard={"pa": 4}, chain_hashes=hashes,
        )
        # The eviction lands AFTER scoring (the forced-eviction regime):
        # the index catches up before the realized report arrives.
        for h in hashes[2:]:
            idx.evict(_keys([h])[0], _entries(["pa"]))
        rec = a.record_realized("r1", "pa", 2)
        assert rec.cause == "stale_index"

    def test_attribution_evicted_on_pod_when_index_still_claims(self):
        hashes = (1, 2, 3, 4)
        idx = self._warm_index(hashes)
        a = RouteAuditor(index=idx, model_name=MODEL)
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4,
            scoreboard={"pa": 4}, chain_hashes=hashes,
        )
        # Index unchanged, pod truth short: phantom locality.
        rec = a.record_realized("r1", "pa", 2)
        assert rec.cause == "evicted_on_pod"

    def test_attribution_without_probe_degrades_to_stale_index(self):
        a = RouteAuditor()  # no index attached
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=4, scoreboard={"pa": 4}
        )
        rec = a.record_realized("r1", "pa", 1)
        assert rec.cause == "stale_index"

    # -- debug payloads ------------------------------------------------------
    def test_debug_audit_payload_filters_and_bad_limit(self):
        a = RouteAuditor()
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=1, scoreboard={"pa": 1},
            trace_id="t1",
        )
        a.record_realized("r1", "pa", 1)
        status, payload = debug_audit_payload(a, {})
        assert status == 200 and len(payload["audits"]) == 1
        status, payload = debug_audit_payload(a, {"request_id": "zz"})
        assert payload["audits"] == []
        status, payload = debug_audit_payload(a, {"trace_id": "t1"})
        assert len(payload["audits"]) == 1
        status, _ = debug_audit_payload(a, {"limit": "bogus"})
        assert status == 400
        status, payload = debug_audit_payload(None, {})
        assert status == 200 and payload == {"enabled": False, "audits": []}

    def test_debug_staleness_payload_disabled_without_tracker(self):
        assert debug_staleness_payload(None, {}) == (200, {"enabled": False})
        t = StalenessTracker(clock=lambda: 1.0)
        assert debug_staleness_payload(t, {})[1]["enabled"] is True


# ---------------------------------------------------------------------------
# BlendedRouter audit hook
# ---------------------------------------------------------------------------


class TestRouterAuditHook:
    def _router(self, score_fn, auditor):
        return BlendedRouter(
            score_fn=score_fn,
            affinity=PrefixAffinityTracker(
                2, 64,
                token_processor=ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=PS)
                ),
            ),
            loads_fn=lambda names: [0.0, 0.0],
            auditor=auditor,
        )

    def test_route_records_decision_with_index_prediction(self):
        a = RouteAuditor()
        router = self._router(lambda toks, names: {"pa": 3, "pb": 1}, a)
        router.route(list(range(16)), ["pa", "pb"], request_id="r1")
        rec = a.record_realized("r1", "pa", 3)
        assert rec.predicted_blocks == 3 and rec.ratio == 1.0

    def test_cold_route_predicts_from_affinity_and_flags_never_stored(self):
        a = RouteAuditor(index=InMemoryIndex())
        router = self._router(lambda toks, names: {}, a)
        toks = list(range(16))
        # First pass warms the affinity memory for pod index 0.
        router.route(toks, ["pa", "pb"], request_id="r0")
        router.route(toks, ["pa", "pb"], request_id="r1")
        rec = a.record_realized("r1", "pa", 0)
        # Index never claimed the chain: the affinity-based optimism is
        # attributed never_stored, not an index fault.
        assert rec.predicted_blocks == len(toks) // PS
        assert rec.cause == "never_stored"

    def test_pull_decision_predicts_pull_blocks(self):
        # A pull decision promises the SOURCE's warm chain lands on the
        # target: predicted = pull_blocks, not the cold target's own
        # score — otherwise every pull drops out of the ratio histogram.
        class AlwaysPull:
            def decide(self, **kw):
                return "pull"

        a = RouteAuditor()
        router = self._router(lambda toks, names: {"pa": 3}, a)
        router.loads_fn = lambda names: [1.0, 0.0]
        router.cost_model = AlwaysPull()
        decision = router.route(
            list(range(16)), ["pa", "pb"], request_id="r-pull"
        )
        assert decision.action == "pull" and decision.pod == "pb"
        rec = a.record_realized("r-pull", "pb", 3)
        assert rec.predicted_blocks == 3 and rec.ratio == 1.0
        assert rec.cause is None and rec.decision == "pull"

    def test_failed_pull_miss_is_attributable(self):
        # Dead peer → cold fallback: the target realizes nothing against
        # the pull promise, and the miss surfaces (never_stored: the
        # index never claimed the chain on the target; the row's
        # decision="pull" names the failed mechanism).
        class AlwaysPull:
            def decide(self, **kw):
                return "pull"

        a = RouteAuditor()
        router = self._router(lambda toks, names: {"pa": 3}, a)
        router.loads_fn = lambda names: [1.0, 0.0]
        router.cost_model = AlwaysPull()
        router.route(list(range(16)), ["pa", "pb"], request_id="r-dead")
        rec = a.record_realized("r-dead", "pb", 0)
        assert rec.predicted_blocks == 3 and rec.ratio == 0.0
        assert rec.cause == "never_stored" and rec.decision == "pull"

    def test_no_auditor_or_no_request_id_records_nothing(self):
        a = RouteAuditor()
        router = self._router(lambda toks, names: {"pa": 2}, a)
        router.route(list(range(8)), ["pa", "pb"])  # no request_id
        assert a.snapshot()["decisions_recorded"] == 0
        router.auditor = None
        router.route(list(range(8)), ["pa", "pb"], request_id="r1")
        assert a.snapshot()["decisions_recorded"] == 0


# ---------------------------------------------------------------------------
# SLO recording
# ---------------------------------------------------------------------------


class TestSLO:
    def test_parse_spec(self):
        (a, b) = parse_slo_spec("ttft:0.5:0.99;itl:0.05:0.95")
        assert a.metric == "ttft" and a.threshold_s == 0.5 and a.target == 0.99
        assert b.label == "itl_le_0.05s_p0.95"
        assert parse_slo_spec("") == []

    @pytest.mark.parametrize(
        "spec", ["ttft:0.5", "e2e:1:0.9", "ttft:0:0.9", "ttft:1:1.5", "ttft:1:0"]
    )
    def test_parse_spec_rejects_malformed(self, spec):
        with pytest.raises(ValueError):
            parse_slo_spec(spec)

    def test_parse_windows(self):
        assert parse_windows("") == (60.0, 300.0)
        assert parse_windows("10,20") == (10.0, 20.0)
        with pytest.raises(ValueError):
            parse_windows("0,10")

    def test_burn_rate_is_violating_fraction_over_budget(self):
        now = [0.0]
        r = SLORecorder(
            parse_slo_spec("ttft:0.5:0.9"), windows_s=(10.0,),
            clock=lambda: now[0],
        )
        for ttft in (0.1, 0.1, 0.1, 1.0):  # 25% violating, budget 10%
            r.observe(ttft, None)
        rates = r.burn_rates()
        assert rates["ttft_le_0.5s_p0.9"]["10s"] == 2.5

    def test_window_pruning(self):
        now = [0.0]
        r = SLORecorder(
            parse_slo_spec("ttft:0.5:0.9"), windows_s=(10.0,),
            clock=lambda: now[0],
        )
        r.observe(1.0, None)  # violation at t=0
        now[0] = 20.0
        r.observe(0.1, None)  # only sample inside the window
        assert r.burn_rates()["ttft_le_0.5s_p0.9"]["10s"] == 0.0

    def test_empty_window_is_none_and_gauge_skipped(self):
        r = SLORecorder(parse_slo_spec("itl:0.05:0.99"), windows_s=(60.0,))
        assert r.burn_rates()["itl_le_0.05s_p0.99"]["60s"] is None
        calls = []
        r.sync_gauges(lambda o, w, v: calls.append((o, w, v)))
        assert calls == []

    def test_none_measurement_skipped(self):
        r = SLORecorder(parse_slo_spec("itl:0.05:0.9"), windows_s=(60.0,))
        r.observe(0.3, None)  # single-token request: no ITL
        assert r.burn_rates()["itl_le_0.05s_p0.9"]["60s"] is None

    def test_malformed_spec_fails_pod_construction(self):
        with pytest.raises(ValueError):
            PodServer(_pod_config("slo-bad", obs_slo="garbage"))


# ---------------------------------------------------------------------------
# Knobs-off parity (the hard contract)
# ---------------------------------------------------------------------------


class TestKnobsOffParity:
    def _run(self, scenario, **cfg_kw):
        server = PodServer(_pod_config("parity-pod", **cfg_kw))
        server.start()

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                await scenario(client, server)
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            server.shutdown()

    def test_pod_response_and_stats_keys_pinned_with_knobs_off(self):
        async def scenario(c, server):
            resp = await c.post(
                "/v1/completions",
                json={"prompt_token_ids": _prompt(0, 10), "max_tokens": 3},
            )
            assert resp.status == 200
            data = await resp.json()
            assert set(data) == {
                "id", "object", "model", "choices", "usage", "ttft_s"
            }
            resp = await c.get("/stats")
            stats = await resp.json()
            assert set(stats) == {
                "pod", "model", "data_parallel_rank", "staged", "waiting",
                "running", "free_pages", "total_pages", "prefill",
                "transfer", "self_heal", "admission", "drain",
            }

        self._run(scenario)

    def test_pod_publishes_no_audit_events_with_knob_off(self):
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=1))
        pool.start()
        link = ChaosLink(pool, "parity-pod", MODEL)
        server = PodServer(
            _pod_config("parity-pod", publish_events=True), publisher=link
        )
        server.start()
        try:
            server.generate(
                _prompt(1, 12), SamplingParams(max_new_tokens=3), timeout=120
            )
            assert pool.drain(timeout=5.0)
        finally:
            server.shutdown()
            pool.shutdown()
        assert server.audits_published == 0
        assert server.slo is None

    def test_pod_slo_and_audit_blocks_absent_with_knobs_off(self):
        async def scenario(c, server):
            stats = await (await c.get("/stats")).json()
            assert "slo" not in stats and "audit" not in stats

        self._run(scenario)

    def test_scorer_stats_keys_pinned_with_knobs_off(self):
        from llm_d_kv_cache_manager_tpu.server.api import (
            ScoringService,
            ServiceConfig,
        )

        svc = ScoringService(
            ServiceConfig(native_index=False, enable_metrics=False)
        )
        assert svc.staleness is None and svc.route_auditor is None

        async def runner():
            ts = TestServer(svc.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                stats = await (await client.get("/stats")).json()
                assert set(stats) == {
                    "fleet", "subscriber", "events_rejected_after_shutdown",
                    "index_size", "index",
                }
                data = await (await client.get("/debug/staleness")).json()
                assert data == {"enabled": False}
                data = await (await client.get("/debug/audit")).json()
                assert data == {"enabled": False, "audits": []}
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            svc.indexer.shutdown()


# ---------------------------------------------------------------------------
# Scoring service with knobs on
# ---------------------------------------------------------------------------


class TestScoringServiceAudit:
    def _svc(self, **kw):
        from llm_d_kv_cache_manager_tpu.server.api import (
            ScoringService,
            ServiceConfig,
        )

        return ScoringService(
            ServiceConfig(native_index=False, enable_metrics=False, **kw)
        )

    def test_audit_knob_records_scoreboard_keyed_by_request_id(self):
        svc = self._svc(obs_audit=True)
        svc.indexer.get_pod_scores = (
            lambda prompt, model, pods, placement=None: {"pa": 5, "pb": 2}
        )

        async def runner():
            ts = TestServer(svc.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.post(
                    "/score_completions",
                    json={
                        "prompt": "x", "model": MODEL, "request_id": "req-9",
                    },
                )
                assert resp.status == 200
                stats = await (await client.get("/stats")).json()
                assert stats["audit"]["decisions_recorded"] == 1
                assert stats["audit"]["pending"] == 1
                assert "staleness" in stats
            finally:
                await client.close()

        try:
            asyncio.run(runner())
            rec = svc.route_auditor.record_realized("req-9", "pa", 5)
            assert rec.ratio == 1.0 and rec.cause is None
        finally:
            svc.indexer.shutdown()

    def test_obs_metrics_adds_scoreboard_and_events_behind_block(self):
        svc = self._svc(obs_metrics=True)
        svc.indexer.get_pod_scores = (
            lambda prompt, model, pods, placement=None: {"pa": 1, "pb": 1}
        )
        assert svc.staleness is not None  # events-behind needs the tracker
        assert svc.route_auditor is None  # audit knob separately gated

        async def runner():
            ts = TestServer(svc.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                await client.post(
                    "/score_completions", json={"prompt": "x", "model": MODEL}
                )
                stats = await (await client.get("/stats")).json()
                assert stats["obs"]["scoreboard_size"] == 2
                assert stats["obs"]["events_behind"] == {}
                # Audit-only blocks stay out without OBS_AUDIT.
                assert "staleness" not in stats and "audit" not in stats
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            svc.indexer.shutdown()


# ---------------------------------------------------------------------------
# Pod RequestAudit publishing
# ---------------------------------------------------------------------------


class TestPodAuditPublish:
    def test_realized_blocks_published_per_finished_request(self):
        idx = InMemoryIndex()
        pool = KVEventsPool(InMemoryIndex(), KVEventsPoolConfig(concurrency=1))
        auditor = RouteAuditor(index=idx, model_name=MODEL)
        pool.audit = auditor
        pool.start()
        link = ChaosLink(pool, "audit-pod", MODEL)
        server = PodServer(
            _pod_config("audit-pod", publish_events=True, obs_audit=True),
            publisher=link,
        )
        server.start()
        prefix = _prompt(30, 16)
        try:
            # Cold pass caches the prefix; warm pass realizes hits on it.
            server.generate(
                prefix + _prompt(31, 4), SamplingParams(max_new_tokens=2),
                timeout=120,
            )
            warm_fut = server.submit(
                prefix + _prompt(32, 4), SamplingParams(max_new_tokens=2),
                request_id="warm-1",
            )
            auditor.record_decision(
                "warm-1", chosen_pod="audit-pod",
                predicted_blocks=len(prefix) // PS,
                scoreboard={"audit-pod": len(prefix) // PS},
            )
            seq = warm_fut.result(timeout=120)
            assert pool.drain(timeout=10.0)
        finally:
            server.shutdown()
            pool.shutdown()
        assert server.audits_published == 2
        assert seq.num_cached_prompt == len(prefix)
        snap = auditor.snapshot()
        # The cold request had no recorded decision (unmatched); the warm
        # one joined with predicted == realized.
        assert snap["unmatched_realized"] == 1
        assert snap["joined"] == 1
        (row,) = auditor.recent(request_id="warm-1")
        assert row["predicted_blocks"] == row["realized_blocks"] == len(prefix) // PS
        assert row["cause"] is None and row["ratio"] == 1.0


# ---------------------------------------------------------------------------
# 2-pod fleet acceptance
# ---------------------------------------------------------------------------


class TestFleetAuditAcceptance:
    """The acceptance pins: predicted == realized on a warm route through
    the REAL path (engines → BlockStored wire → index → BlendedRouter →
    serve → RequestAudit wire → join), and a forced eviction between
    scoring and serving attributes ``stale_index``."""

    def _fleet(self):
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            )
        )
        fh = FleetHealth(FleetHealthConfig())
        now = [time.time()]
        tracker = StalenessTracker(clock=lambda: now[0])
        auditor = RouteAuditor(
            index=indexer.kv_block_index, fleet_health=fh, model_name=MODEL
        )
        pool = KVEventsPool(
            indexer.kv_block_index,
            KVEventsPoolConfig(concurrency=2),
            health=fh,
            staleness=tracker,
            audit=auditor,
        )
        pool.start()
        pods = {}
        links = {}
        for name in ("pod-a", "pod-b"):
            links[name] = ChaosLink(pool, name, MODEL)
            pods[name] = PodServer(
                _pod_config(name, publish_events=True, obs_audit=True),
                publisher=links[name],
            )
            pods[name].start()
        router = BlendedRouter(
            score_fn=lambda toks, names: indexer.score_tokens(
                toks, MODEL, names
            ),
            affinity=PrefixAffinityTracker(
                2, 64,
                token_processor=ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=PS)
                ),
            ),
            loads_fn=lambda names: [
                pods[n].queue_depth for n in names
            ],
            auditor=auditor,
        )
        return indexer, pool, pods, links, router, auditor, tracker, now

    def test_warm_route_predicted_equals_realized(self):
        indexer, pool, pods, links, router, auditor, tracker, now = self._fleet()
        names = ["pod-a", "pod-b"]
        prefix = _prompt(40, 16)
        try:
            # Warm pod-a through the real serving path; its BlockStored
            # events reach the index over the (in-process) wire.
            pods["pod-a"].generate(
                prefix + _prompt(41, 4), SamplingParams(max_new_tokens=2),
                timeout=120,
            )
            assert pool.drain(timeout=10.0)
            prompt = prefix + _prompt(42, 4)
            decision = router.route(prompt, names, request_id="acc-1")
            assert decision.pod == "pod-a"
            assert decision.index_score == len(prefix) // PS
            seq = pods["pod-a"].submit(
                prompt, SamplingParams(max_new_tokens=2), request_id="acc-1"
            ).result(timeout=120)
            assert seq.num_cached_prompt == len(prefix)
            assert pool.drain(timeout=10.0)
        finally:
            for p in pods.values():
                p.shutdown()
            pool.shutdown()
            indexer.shutdown()
        (row,) = auditor.recent(request_id="acc-1")
        assert row["predicted_blocks"] == len(prefix) // PS
        assert row["realized_blocks"] == row["predicted_blocks"]
        assert row["ratio"] == 1.0 and row["cause"] is None
        # The staleness probes saw the fleet's event traffic.
        assert tracker.snapshot()["events_observed"] > 0

    def test_forced_eviction_after_scoring_attributes_stale_index(self):
        indexer, pool, pods, links, router, auditor, tracker, now = self._fleet()
        names = ["pod-a", "pod-b"]
        prefix = _prompt(50, 16)
        prompt = prefix + _prompt(51, 4)
        try:
            pods["pod-a"].generate(
                prefix + _prompt(52, 4), SamplingParams(max_new_tokens=2),
                timeout=120,
            )
            assert pool.drain(timeout=10.0)
            decision = router.route(prompt, names, request_id="evict-1")
            assert decision.pod == "pod-a" and decision.index_score > 0
            # Forced eviction AFTER scoring: pod-a's pool churns and it
            # publishes BlockRemoved for the scored chain — exactly what
            # capacity pressure does between scoring and serving.
            hashes = indexer.token_processor.prefix_hashes(prompt)
            links["pod-a"].publish(
                [BlockRemoved(block_hashes=list(hashes))]
            )
            assert pool.drain(timeout=10.0)
            # The pod's realized report arrives over the same wire.
            links["pod-a"].publish(
                [RequestAudit(request_id="evict-1", realized_blocks=0)]
            )
            assert pool.drain(timeout=10.0)
        finally:
            for p in pods.values():
                p.shutdown()
            pool.shutdown()
            indexer.shutdown()
        (row,) = auditor.recent(request_id="evict-1")
        assert row["cause"] == "stale_index"
        assert auditor.snapshot()["miss_causes"]["stale_index"] == 1
