"""Chat-templating processor tests with golden-output validation.

The reference validates its three-language rendering bridge against vLLM's
rendered prompt (``cgo_functions_test.go`` TestVLLMValidation, network +
Python env required). Here the renderer IS transformers'
``render_jinja_template`` — the same function serving engines call — so the
goldens below are frozen outputs for a Llama-3-style template: any rendering
drift (which would silently break hash alignment between chat scoring and
the engine) fails these tests. No network needed: templates are embedded.
"""

import threading

import pytest

from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    FetchTemplateRequest,
    RenderRequest,
)

LLAMA3_STYLE_TPL = (
    "{{ bos_token }}{% for message in messages %}"
    "<|start_header_id|>{{ message['role'] }}<|end_header_id|>\n\n"
    "{{ message['content'] }}<|eot_id|>{% endfor %}"
    "{% if add_generation_prompt %}<|start_header_id|>assistant<|end_header_id|>\n\n{% endif %}"
)

GOLDEN = (
    "<|begin_of_text|><|start_header_id|>system<|end_header_id|>\n\n"
    "You are terse.<|eot_id|><|start_header_id|>user<|end_header_id|>\n\n"
    "2+2?<|eot_id|><|start_header_id|>assistant<|end_header_id|>\n\n"
)

CONVO = [
    {"role": "system", "content": "You are terse."},
    {"role": "user", "content": "2+2?"},
]


@pytest.fixture
def proc():
    p = ChatTemplatingProcessor()
    p.initialize()
    yield p
    p.finalize()


class TestGoldenRendering:
    def test_llama3_style_golden(self, proc):
        out = proc.render_chat_template(
            RenderRequest(
                conversations=[CONVO],
                chat_template=LLAMA3_STYLE_TPL,
                template_vars={"bos_token": "<|begin_of_text|>"},
            )
        )
        assert out.rendered_chats == [GOLDEN]

    def test_no_generation_prompt(self, proc):
        out = proc.render_chat_template(
            RenderRequest(
                conversations=[CONVO],
                chat_template=LLAMA3_STYLE_TPL,
                add_generation_prompt=False,
                template_vars={"bos_token": "<|begin_of_text|>"},
            )
        )
        assert out.rendered_chats[0] == GOLDEN.rsplit(
            "<|start_header_id|>assistant", 1
        )[0]

    def test_multiple_conversations_batched(self, proc):
        convo2 = [{"role": "user", "content": "hi"}]
        out = proc.render_chat_template(
            RenderRequest(
                conversations=[CONVO, convo2],
                chat_template=LLAMA3_STYLE_TPL,
                template_vars={"bos_token": "<|begin_of_text|>"},
            )
        )
        assert len(out.rendered_chats) == 2
        assert out.rendered_chats[0] == GOLDEN
        assert "hi" in out.rendered_chats[1]

    def test_long_conversation(self, proc):
        """Reference tests long conversations through the bridge; rendering
        must stay linear and lossless."""
        convo = []
        for i in range(100):
            convo.append({"role": "user", "content": f"message {i}"})
            convo.append({"role": "assistant", "content": f"reply {i}"})
        out = proc.render_chat_template(
            RenderRequest(
                conversations=[convo],
                chat_template=LLAMA3_STYLE_TPL,
                template_vars={"bos_token": ""},
            )
        )
        rendered = out.rendered_chats[0]
        assert rendered.count("<|eot_id|>") == 200
        assert "message 99" in rendered and "reply 99" in rendered


class TestTemplateCache:
    def test_explicit_template_bypasses_cache(self, proc):
        tpl, vars_ = proc.fetch_chat_template(
            FetchTemplateRequest(model="any", chat_template=LLAMA3_STYLE_TPL)
        )
        assert tpl == LLAMA3_STYLE_TPL and vars_ == {}

    def test_clear_caches(self, proc):
        proc._template_cache["k"] = ("t", {})
        proc.clear_caches()
        assert proc._template_cache == {}

    def test_concurrent_rendering(self, proc):
        errors = []

        def worker():
            try:
                for _ in range(10):
                    out = proc.render_chat_template(
                        RenderRequest(
                            conversations=[CONVO],
                            chat_template=LLAMA3_STYLE_TPL,
                            template_vars={"bos_token": "<|begin_of_text|>"},
                        )
                    )
                    assert out.rendered_chats == [GOLDEN]
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
