"""Chunked prefill + mixed-step scheduling tests.

Load-bearing invariants:
- with ``chunked_prefill_tokens`` unset the scheduler/engine are
  bit-identical to the legacy either-or engine (the existing
  ``test_engine.py`` determinism tests pin the engine side; the scheduler
  unit tests here pin the schedule shapes);
- with chunking ON, greedy outputs are bit-identical to the unchunked
  engine — including prefix-cache-hit prompts and preemption mid-prefill;
- a waiting/ingesting long prompt never starves running decode lanes: every
  mixed step carries the lanes, and they commit tokens during ingest.
"""

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManager,
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    Scheduler,
    SchedulerConfig,
    Sequence,
)

PS = 4


def _engine(total_pages=64, decode_batch=4, chunked=None, **kw):
    cfg = EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=PS),
        scheduler=SchedulerConfig(
            max_prefill_batch=4, chunked_prefill_tokens=chunked
        ),
        max_model_len=64,
        decode_batch_size=decode_batch,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )
    return Engine(cfg)


def _prompt(seed, n):
    return list(np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))


class TestChunkedSchedulerUnit:
    """Scheduler-level behavior, no device dispatch."""

    def _sched(self, chunked=8, total_pages=64, **kw):
        bm = BlockManager(BlockManagerConfig(total_pages=total_pages, page_size=PS))
        return Scheduler(
            bm,
            SchedulerConfig(
                max_prefill_batch=4,
                chunked_prefill_tokens=chunked,
                chunk_align=8,
                **kw,
            ),
        )

    def test_long_prompt_never_starves_decode_lanes(self):
        # A running decode lane + a waiting 40-token prompt with budget 8:
        # EVERY step until the prompt finishes ingesting must carry the
        # decode lane (the stall-free property under test).
        sched = self._sched(chunked=8)
        lane = Sequence(prompt_tokens=_prompt(0, 6))
        sched.block_manager.allocate(lane)
        lane.num_prefilled = 6
        sched.on_prefill_done([lane])

        long = Sequence(prompt_tokens=_prompt(1, 40))
        sched.add(long)
        steps = 0
        while long.prompt_remaining > 0:
            out = sched.schedule()
            assert out.decode == [lane], "decode lane starved during ingest"
            assert out.prefill == [long] and len(out.chunks) == 1
            # simulate the engine committing the chunk
            long.num_prefilled += out.chunks[0]
            steps += 1
            assert steps < 50
        assert steps == 5  # 40 tokens / 8-token budget
        sched.on_prefill_done([long])
        assert long in sched.running and not sched.prefilling

    def test_nonfinal_chunks_are_aligned_final_is_remainder(self):
        sched = self._sched(chunked=12)  # not an align multiple
        seq = Sequence(prompt_tokens=_prompt(2, 21))
        sched.add(seq)
        sizes = []
        while seq.prompt_remaining > 0:
            out = sched.schedule()
            sizes.append(out.chunks[0])
            seq.num_prefilled += out.chunks[0]
        # budget 12 floors to align=8 for non-final chunks; remainder last
        assert sizes == [8, 8, 5]
        for s in sizes[:-1]:
            assert s % 8 == 0

    def test_budget_smaller_than_align_still_progresses(self):
        sched = self._sched(chunked=3)
        seq = Sequence(prompt_tokens=_prompt(3, 17))
        sched.add(seq)
        out = sched.schedule()
        assert out.chunks == [8]  # clamped up to one alignment unit

    def test_max_prefill_tokens_below_align_cannot_livelock(self):
        # Regression: the align clamp must win over max_prefill_tokens —
        # a budget pulled below one alignment unit would otherwise produce
        # zero-token chunks forever (allocate/rollback every step while
        # has_work stays True).
        sched = self._sched(chunked=16, max_prefill_tokens=4)
        seq = Sequence(prompt_tokens=_prompt(9, 30))
        sched.add(seq)
        steps = 0
        while seq.prompt_remaining > 0:
            out = sched.schedule()
            assert out.chunks and out.chunks[0] > 0
            seq.num_prefilled += out.chunks[0]
            steps += 1
            assert steps < 20

    def test_admission_rolls_back_when_budget_exhausted(self):
        # First prompt eats the whole budget; the second must NOT hold
        # pages while doing zero work this step.
        sched = self._sched(chunked=8)
        a = Sequence(prompt_tokens=_prompt(4, 24))
        b = Sequence(prompt_tokens=_prompt(5, 24))
        sched.add(a)
        sched.add(b)
        out = sched.schedule()
        assert out.prefill == [a] and out.chunks == [8]
        assert not b.block_table and b in sched.waiting
        free_with_b_waiting = sched.block_manager.num_free
        # a's pages are held, b's are not
        assert free_with_b_waiting == 64 - 1 - 6  # page 0 reserved, a = 6 pages

    def test_resume_prioritized_over_new_admission(self):
        sched = self._sched(chunked=16)
        a = Sequence(prompt_tokens=_prompt(6, 24))
        sched.add(a)
        out = sched.schedule()
        assert out.prefill == [a]
        a.num_prefilled += out.chunks[0]
        b = Sequence(prompt_tokens=_prompt(7, 24))
        sched.add(b)
        out = sched.schedule()
        # a resumes first; leftover budget admits b
        assert out.prefill[0] is a and out.chunks[0] == 8
        assert out.prefill[1] is b and out.chunks[1] == 8

    def test_legacy_mode_unchanged_when_knob_unset(self):
        sched = self._sched(chunked=None)
        a = Sequence(prompt_tokens=_prompt(8, 12))
        sched.add(a)
        out = sched.schedule()
        assert out.prefill == [a] and out.chunks is None and out.decode == []
        a.num_prefilled = 12
        sched.on_prefill_done([a])
        out = sched.schedule()
        assert out.prefill == [] and out.decode == [a]


class TestChunkedPrefillParity:
    """Greedy outputs must be bit-identical chunked vs unchunked. Also the
    tier-1 CPU smoke of the mixed-step path (fast, runs every commit)."""

    def test_single_long_prompt_matches(self):
        outs = []
        for chunked in (None, 8):
            eng = _engine(chunked=chunked)
            s = eng.add_request(_prompt(10, 40), SamplingParams(max_new_tokens=6))
            eng.run_until_complete()
            assert s.error is None
            outs.append(s.output_tokens)
        assert outs[0] == outs[1]
        assert len(outs[0]) == 6

    def test_mixed_arrivals_match_and_decode_advances_during_ingest(self):
        def drive(chunked):
            eng = _engine(chunked=chunked)
            a = eng.add_request(_prompt(11, 6), SamplingParams(max_new_tokens=14))
            b = eng.add_request(_prompt(12, 9), SamplingParams(max_new_tokens=14))
            for _ in range(3):
                eng.step()
            c = eng.add_request(_prompt(13, 41), SamplingParams(max_new_tokens=5))
            during_ingest = 0
            while c.num_generated == 0 and eng.has_work:
                g0 = a.num_generated + b.num_generated
                eng.step()
                if c.num_generated == 0:
                    during_ingest += a.num_generated + b.num_generated - g0
            eng.run_until_complete()
            return [a.generated_tokens, b.generated_tokens, c.generated_tokens], during_ingest

        base, stalled = drive(None)
        chk, streamed = drive(8)
        assert base == chk
        # The mechanism: with either-or scheduling the lanes commit nothing
        # while the 41-token prompt prefills; with a 8-token budget they
        # keep streaming through the ~5 chunk steps.
        assert stalled == 0
        assert streamed >= 4

    def test_prefix_cache_hit_prompts_match(self):
        shared = _prompt(42, 16)  # 4 full pages
        outs = []
        for chunked in (None, 8):
            eng = _engine(chunked=chunked)
            a = eng.add_request(
                shared + _prompt(14, 20), SamplingParams(max_new_tokens=4)
            )
            eng.run_until_complete()
            b = eng.add_request(
                shared + _prompt(15, 24), SamplingParams(max_new_tokens=4)
            )
            eng.run_until_complete()
            assert b.num_cached_prompt == 16
            outs.append((a.output_tokens, b.output_tokens))
        assert outs[0] == outs[1]

    def test_chunked_pages_feed_prefix_cache_mid_prefill(self):
        # Pages registered by non-final chunks are real prefix-cache
        # entries: a follow-up sharing the long prompt's prefix cache-hits
        # pages written chunk by chunk.
        p = _prompt(16, 40)
        eng = _engine(chunked=8)
        a = eng.add_request(p, SamplingParams(max_new_tokens=3))
        eng.run_until_complete()
        b = eng.add_request(list(p), SamplingParams(max_new_tokens=3))
        eng.run_until_complete()
        assert b.num_cached_prompt >= 36  # all but the last partial page
        assert a.output_tokens == b.output_tokens

    def test_preemption_mid_prefill_matches(self):
        # Pool sized so the decode lane's growth must preempt the long
        # prompt mid-prefill (chunked mode holds its pages across steps);
        # everything still completes with identical tokens.
        def drive(chunked):
            eng = _engine(chunked=chunked, total_pages=16, decode_batch=2)
            a = eng.add_request(_prompt(17, 8), SamplingParams(max_new_tokens=20))
            eng.step()  # a prefills and starts decoding
            b = eng.add_request(_prompt(18, 33), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
            assert a.error is None and b.error is None
            return [a.generated_tokens, b.generated_tokens]

        base = drive(None)
        chk = drive(8)
        assert base == chk
        assert len(base[0]) == 20 and len(base[1]) == 4

    def test_env_knob_wires_chunked_prefill(self, monkeypatch):
        from llm_d_kv_cache_manager_tpu.server.serve import PodServerConfig

        monkeypatch.setenv("CHUNKED_PREFILL_TOKENS", "512")
        cfg = PodServerConfig.from_env()
        assert cfg.engine.scheduler.chunked_prefill_tokens == 512
        monkeypatch.setenv("CHUNKED_PREFILL_TOKENS", "0")
        assert PodServerConfig.from_env().engine.scheduler.chunked_prefill_tokens is None

    def test_rejects_bad_budget(self):
        with pytest.raises(ValueError, match="chunked_prefill_tokens"):
            _engine(chunked=0)


class TestChunkedInterference:
    """Heavier chunked-prefill coverage: the interference microbenchmark
    as a test, plus parity sweeps against the other decode paths
    (auto-marked slow in conftest; CI's full job runs them)."""

    @pytest.mark.parametrize(
        "kw",
        [
            dict(decode_steps_per_iter=3),
            dict(decode_steps_per_iter=3, decode_pipeline=True),
            dict(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2),
            dict(spec_decode="prompt_lookup", spec_k=3, spec_ngram=2,
                 spec_rounds=3),
        ],
    )
    def test_parity_with_other_decode_paths(self, kw):
        # Chunked ingest composes with fused/pipelined/speculative decode:
        # token streams stay identical to the unchunked engine running the
        # same decode config.
        rep = _prompt(20, 3) * 6  # repetition-heavy lane (exercises spec)
        prompts = [rep, _prompt(21, 9), _prompt(22, 38)]
        streams = []
        for chunked in (None, 8):
            eng = _engine(chunked=chunked, **kw)
            seqs = []
            for p in prompts:
                seqs.append(eng.add_request(p, SamplingParams(max_new_tokens=8)))
                eng.step()
            eng.run_until_complete()
            assert all(s.error is None for s in seqs), kw
            streams.append([s.generated_tokens for s in seqs])
        assert streams[0] == streams[1], kw

    def test_interference_microbench_mechanism(self):
        """The microbenchmark's mechanism, asserted deterministically
        (token counts, not wall time): decode lanes keep committing while
        a long prompt ingests chunked, and stall completely unchunked."""

        def drive(chunked):
            eng = _engine(chunked=chunked, total_pages=96)
            lanes = [
                eng.add_request(_prompt(30 + i, 6), SamplingParams(max_new_tokens=40))
                for i in range(2)
            ]
            while any(s.num_generated == 0 for s in lanes):
                eng.step()
            long = eng.add_request(_prompt(33, 48), SamplingParams(max_new_tokens=4))
            during = 0
            while long.num_generated == 0 and eng.has_work:
                g0 = sum(s.num_generated for s in lanes)
                eng.step()
                if long.num_generated == 0:
                    during += sum(s.num_generated for s in lanes) - g0
            eng.run_until_complete()
            assert long.error is None
            return during, [s.generated_tokens for s in lanes]

        stalled, base = drive(None)
        streamed, chk = drive(8)
        assert stalled == 0  # either-or: whole-prompt prefill stalls lanes
        assert streamed >= 4  # mixed steps: lanes stream through ingest
        assert base == chk
