"""monotonic-time: rate/deadline/backoff math must not read the wall clock.

``time.time()`` steps under NTP slew; a deadline computed from it can
fire early, late, or never — the breaker backoff and Retry-After bugs
this rule exists for. Every ``time.time()`` call is flagged; the only
legitimate uses are timestamps that cross the wire or are shown to
humans, and those carry a justified ``# kvlint: disable=monotonic-time``.
"""

from __future__ import annotations

import ast

from tools.kvlint.core import Finding, ModuleUnit, RepoContext

RULE = "monotonic-time"

#: module aliases ``time`` travels under in this tree
_TIME_NAMES = {"time", "_time"}


def check(unit: ModuleUnit, ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    # ``from time import time`` style — only if the module imports the
    # function by name (heuristic: a bare-name call is then the imported
    # function). Computed once; the node loop below only consults it.
    imports_bare_time = any(
        isinstance(n, ast.ImportFrom)
        and n.module == "time"
        and any(a.name == "time" for a in n.names)
        for n in ast.walk(unit.tree)
    )
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit = False
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "time"
            and isinstance(fn.value, ast.Name)
            and fn.value.id in _TIME_NAMES
        ):
            hit = True  # time.time()
        elif isinstance(fn, ast.Name) and fn.id == "time":
            hit = imports_bare_time
        if hit:
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.rel,
                    line=node.lineno,
                    message=(
                        "time.time() in library code: use time.monotonic() for "
                        "rate/deadline/backoff arithmetic; wall clock is only "
                        "for timestamps that cross the wire (suppress with a "
                        "justification if so)"
                    ),
                )
            )
    return findings
