"""Two-hop request orchestration for disaggregated prefill/decode serving.

``DisaggCoordinator`` is the serving-plane composition layer: given a
fleet of ``PodServer``s (role-assigned via ``POD_ROLE``), it drives each
request through

1. **plan** — ``TwoHopPlanner`` picks the prefill pod (index warmth +
   measured prefill rate + queue) and the decode pod (queue-depth/ITL
   headroom), skipping draining/dead/breaker-open pods;
2. **prefill hop** — submit to the prefill pod (its role clamps the
   request to the first token; admission sheds HERE, so overload answers
   arrive as a fast 429-style ``AdmissionError`` with a Retry-After hint
   before any decode-tier capacity is touched);
3. **handoff** — the finished chain stays registered on the prefill pod
   (its ``PrefillComplete`` event announces supply); the coordinator
   carries the first token forward and names the prefill pod's transfer
   endpoint as the decode hop's ``pull_source``;
4. **decode hop** — submit ``prompt + [first_token]`` to the decode pod,
   which admits the request in the PR 7 ``importing`` state, pulls the
   chain asynchronously, cache-hits the imported pages, and streams the
   remaining tokens.

Failure handling is strictly "never worse than today": a hop that dies
or drains mid-flight is excluded and the request re-planned (up to
``max_replans`` times); when no two-pod plan exists the request serves
single-pod exactly as the legacy fleet would. Deadlines span both hops —
each hop receives only the remaining budget. With tracing enabled the
whole request is ONE trace: ``disagg.request`` parents both pods'
``pod.request`` spans plus a ``disagg.handoff`` span covering the
gap between the prefill pod's first token and the decode admission.

This coordinator runs in-process over ``PodServer`` objects (the form
the tests, chaos harness, and bench fleet use). An HTTP deployment
embeds the same logic at the router: the planner inputs are all carried
by heartbeats and ``/stats``, and both hops are plain ``/v1/completions``
calls (the decode hop adding ``X-Pull-Source``).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from dataclasses import dataclass, replace
from typing import Dict, Optional, Sequence

from ...obs.tracing import Tracer
from ...utils import get_logger
from ..router import DisaggPlan, PlanError, PodView, TwoHopPlanner

log = get_logger("kvcache.disagg")


@dataclass
class DisaggConfig:
    #: re-plan attempts per request after a hop fails mid-flight (dead /
    #: draining pod). Each re-plan excludes the failed pod; exhausting the
    #: budget surfaces the last error. 1 covers the single-failure chaos
    #: modes; raise for fleets where correlated restarts are common.
    max_replans: int = 1
    #: cap on waiting for any single hop's Future (seconds) — a wedged pod
    #: must not hold the request forever even without a client deadline.
    hop_timeout_s: float = 120.0


@dataclass
class DisaggResult:
    """One served request: the combined view of both hops."""

    tokens: list[int]
    finish_reason: str
    #: "disagg" (two hops ran) or "single" (fallback / planner collapse)
    mode: str
    prefill_pod: Optional[str]
    decode_pod: Optional[str]
    replans: int = 0
    trace_id: Optional[str] = None
    #: prefill-hop TTFT (the user-visible first-token latency)
    ttft_s: Optional[float] = None
    #: prompt tokens the decode hop served from cache (imported chain +
    #: any local warmth) — the handoff-efficiency signal
    decode_cached_tokens: int = 0
    handoff_s: Optional[float] = None


def views_from_pods(pods: Dict[str, "object"]) -> list[PodView]:
    """Planner views from live in-process ``PodServer``s: role and
    endpoint from config, draining/alive from the pod, queue depth and
    the prefill-rate EMA from the engine — the same signals heartbeats
    and ``/stats`` carry for an HTTP deployment. A pod whose export
    endpoint has an OPEN circuit breaker at any peer is marked
    ``breaker_open`` (a pull through it would skip straight to cold)."""
    open_endpoints = set()
    for pod in pods.values():
        open_endpoints |= pod.open_breaker_endpoints
    views = []
    for name, pod in pods.items():
        endpoint = pod.config.transfer_endpoint
        views.append(
            PodView(
                name=name,
                role=pod.config.pod_role,
                transfer_endpoint=endpoint,
                draining=pod.is_draining,
                dead=not pod.is_alive,
                breaker_open=endpoint is not None and endpoint in open_endpoints,
                queue_depth=pod.queue_depth,
                prefill_rate=pod.prefill_rate,
            )
        )
    return views


class DisaggCoordinator:
    """Serving-plane driver for two-hop (prefill pod → decode pod)
    requests, with single-pod fallback. Thread-safe: ``generate`` may be
    called concurrently (bench load generators, chaos harness)."""

    def __init__(
        self,
        pods: Dict[str, "object"],
        config: Optional[DisaggConfig] = None,
        *,
        score_fn=None,
        views_fn=None,
        tracer: Optional[Tracer] = None,
    ):
        """``pods``: name → ``PodServer``. ``score_fn(tokens, names)``:
        index warmth read (e.g. ``KVCacheIndexer.score_tokens`` partially
        applied), None = warmth-blind placement. ``views_fn``: override
        for the planner-view snapshot (tests inject synthetic fleets);
        defaults to ``views_from_pods``."""
        self.pods = pods
        self.config = config or DisaggConfig()
        self.planner = TwoHopPlanner(score_fn)
        self.tracer = tracer or Tracer(enabled=False)
        self._views_fn = views_fn or (lambda: views_from_pods(self.pods))
        self._mu = threading.Lock()
        self.handoffs = 0  # guarded_by: _mu
        self.single_pod_served = 0  # guarded_by: _mu
        self.replans = 0  # guarded_by: _mu

    # -- internals -----------------------------------------------------------
    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        # Floor at ~0, never negative-to-None: an exhausted budget must
        # reach the pod as an (already expired) deadline so the scheduler
        # sheds it with finish_reason="deadline" — not as "no deadline".
        return max(deadline - time.monotonic(), 1e-3)

    def _hop_timeout(self, deadline: Optional[float]) -> float:
        rem = self._remaining(deadline)
        if rem is None:
            return self.config.hop_timeout_s
        # A small grace past the deadline: the pod itself sheds/finishes
        # the sequence at the deadline (finish_reason="deadline") and the
        # result must travel back rather than racing a client-side cutoff.
        return min(self.config.hop_timeout_s, max(rem, 0.0) + 5.0)

    def _run_hop(self, pod, fut, deadline: Optional[float]):
        """Wait out one hop's Future; a wedged pod gets its sequence
        aborted (pages released) before the timeout propagates."""
        try:
            return fut.result(timeout=self._hop_timeout(deadline))
        except FuturesTimeout:
            try:
                pod.abort(fut.request_id).result(timeout=30)
            except Exception:
                log.exception("post-timeout hop abort failed")
            raise

    def _single_pod(
        self, pod_name, tokens, sampling, deadline, span, replans, tenant=""
    ) -> DisaggResult:
        """Legacy one-pod serving (the fallback arm): exactly what the
        non-disagg fleet does today. Its failures re-plan like any hop's:
        a dead/draining/wedged pod raises ``_HopFailed`` so the caller
        excludes it and picks the next healthy pod — only admission sheds
        surface directly (shedding IS the overload design)."""
        from ...server.serve import AdmissionError, DrainingError

        pod = self.pods[pod_name]
        try:
            fut = pod.submit(
                list(tokens),
                sampling,
                deadline_s=self._remaining(deadline),
                trace_ctx=span.context,
                tenant=tenant,
            )
            seq = self._run_hop(pod, fut, deadline)
        except (DrainingError, FuturesTimeout) as e:
            raise _HopFailed(pod_name, "single", e)
        except RuntimeError as e:
            if isinstance(e, AdmissionError):
                raise
            raise _HopFailed(pod_name, "single", e)
        with self._mu:
            self.single_pod_served += 1
        out = list(seq.generated_tokens)
        # Same derivation as the HTTP handler: an engine-reported reason
        # wins; otherwise a trailing stop token is "stop" even at the cap.
        stopped = bool(out) and out[-1] in sampling.stop_token_ids
        return DisaggResult(
            tokens=out,
            finish_reason=seq.finish_reason or ("stop" if stopped else "length"),
            mode="single",
            prefill_pod=None,
            decode_pod=pod_name,
            replans=replans,
            ttft_s=seq.ttft,
            decode_cached_tokens=seq.num_cached_prompt,
        )

    # -- the request path ----------------------------------------------------
    def generate(
        self,
        tokens: Sequence[int],
        sampling=None,
        *,
        deadline_s: Optional[float] = None,
        tenant: str = "",
    ) -> DisaggResult:
        """Serve one request through the two-hop pipeline (or the
        single-pod fallback). Raises ``AdmissionError`` when the prefill
        tier sheds (carrying the Retry-After hint), ``PlanError`` when no
        healthy pod can serve at all, and whatever terminal error the
        last re-plan attempt hit. ``tenant`` (TENANT_QOS) rides every hop
        — the prefill tier enforces the same per-tenant budgets the
        decode tier does, so a tenant's flood sheds at ingest."""
        from ...server.sequence import SamplingParams

        sampling = sampling or SamplingParams()
        deadline = (
            time.monotonic() + deadline_s
            if deadline_s is not None and deadline_s > 0
            else None
        )
        span = self.tracer.start_span(
            "disagg.request", attrs={"prompt_tokens": len(tokens)}
        )
        trace_id = span.context.trace_id if span.context is not None else None
        try:
            result = self._generate_planned(
                tokens, sampling, deadline, span, tenant
            )
            result.trace_id = trace_id
            span.set_attr("mode", result.mode)
            span.set_attr("replans", result.replans)
            span.set_attr("finish", result.finish_reason)
            return result
        except Exception as e:
            span.set_attr("error", repr(e))
            raise
        finally:
            span.end()

    def _generate_planned(
        self, tokens, sampling, deadline, span, tenant=""
    ) -> DisaggResult:
        exclude: set = set()
        #: one re-plan budget shared by both hops (the decode hop re-plans
        #: in place to reuse the finished prefill; its attempts count here)
        state = {"replans": 0}
        last_err: Optional[Exception] = None
        while True:
            try:
                plan = self.planner.plan(tokens, self._views_fn(), exclude)
            except PlanError:
                if last_err is not None:
                    raise last_err
                raise
            try:
                if plan.mode == "single":
                    return self._single_pod(
                        plan.decode_pod, tokens, sampling, deadline, span,
                        state["replans"], tenant,
                    )
                return self._two_hop(
                    plan, tokens, sampling, deadline, span, state, exclude,
                    tenant,
                )
            except _HopFailed as hf:
                # Dead/draining pod mid-flight: exclude it and re-plan.
                # AdmissionError is deliberately NOT retried — shedding at
                # the prefill tier is the overload design, and bouncing a
                # shed request around the fleet re-overloads it.
                exclude.add(hf.pod)
                last_err = hf.cause
                # The counter (stats too) ticks only when a retry actually
                # follows: an exhausted budget surfaces the failure, it is
                # not itself a re-plan.
                if state["replans"] >= self.config.max_replans:
                    raise last_err
                state["replans"] += 1
                with self._mu:
                    self.replans += 1
                log.warning(
                    "disagg hop failed; re-planning",
                    pod=hf.pod,
                    hop=hf.hop,
                    error=repr(hf.cause),
                )

    def _two_hop(
        self, plan: DisaggPlan, tokens, sampling, deadline, span, state,
        exclude, tenant="",
    ) -> DisaggResult:
        from ...server.serve import DrainingError

        prefill_pod = self.pods[plan.prefill_pod]
        decode_pod_name = plan.decode_pod
        # -- hop 1: ingest at the prefill tier, stop at first token ---------
        try:
            pfut = prefill_pod.submit(
                list(tokens),
                replace(sampling, max_new_tokens=1),
                deadline_s=self._remaining(deadline),
                trace_ctx=span.context,
                tenant=tenant,
            )
            pseq = self._run_hop(prefill_pod, pfut, deadline)
        except (DrainingError, FuturesTimeout) as e:
            # A wedged prefill pod (hop timeout, sequence already aborted by
            # _run_hop) is as re-plannable as a draining one.
            raise _HopFailed(plan.prefill_pod, "prefill", e)
        except RuntimeError as e:
            # AdmissionError (a RuntimeError subclass) re-raises untouched:
            # shedding at the prefill tier IS the overload design, and the
            # Retry-After hint must reach the client. Everything else here
            # is a dead pod — re-plannable.
            from ...server.serve import AdmissionError

            if isinstance(e, AdmissionError):
                raise
            raise _HopFailed(plan.prefill_pod, "prefill", e)
        t_handoff = time.monotonic()
        first = list(pseq.generated_tokens)
        if not first and pseq.finish_reason in ("deadline", "abort"):
            # Shed before ingest (deadline expired while queued, or the
            # request was aborted): the honest end-to-end answer — the
            # deadline clamp spans both hops, and the decode tier is never
            # touched for a request that already missed it.
            return DisaggResult(
                tokens=[],
                finish_reason=pseq.finish_reason,
                mode="disagg",
                prefill_pod=plan.prefill_pod,
                decode_pod=None,
                replans=state["replans"],
            )
        if pseq.error or not first:
            raise _HopFailed(
                plan.prefill_pod,
                "prefill",
                RuntimeError(pseq.error or "prefill hop produced no token"),
            )
        done_reason = pseq.finish_reason
        stop_hit = first[-1] in sampling.stop_token_ids
        if (
            sampling.max_new_tokens <= 1
            or stop_hit
            or done_reason in ("deadline", "abort")
        ):
            # Nothing left to decode (single-token request, immediate stop,
            # or the deadline expired during ingest): the prefill hop's
            # answer IS the answer — no chain ever moved, so `handoffs`
            # stays untouched. finish_reason mirrors single-pod truth.
            reason = done_reason or ("stop" if stop_hit else "length")
            return DisaggResult(
                tokens=first,
                finish_reason=reason,
                mode="disagg",
                prefill_pod=plan.prefill_pod,
                decode_pod=None,
                replans=state["replans"],
                ttft_s=pseq.ttft,
            )
        # -- hop 2: pull the chain + stream tokens at the decode tier -------
        decode_sampling = replace(
            sampling, max_new_tokens=sampling.max_new_tokens - 1
        )
        handoff_tokens = list(tokens) + first
        while True:
            decode_pod = self.pods[decode_pod_name]
            # A re-plan may land the decode hop on the prefill pod itself
            # (mixed fleets: its queue is shallow after the 1-token stop):
            # the chain is already local there, so naming its own endpoint
            # as pull_source would re-transfer every block to itself.
            pull_source = (
                plan.pull_source
                if decode_pod_name != plan.prefill_pod
                else None
            )
            try:
                dfut = self._submit_decode_hop(
                    decode_pod, handoff_tokens, decode_sampling, deadline,
                    span, pull_source, prompt_len=len(tokens), tenant=tenant,
                )
                dseq = self._run_hop(decode_pod, dfut, deadline)
            except (DrainingError, RuntimeError, FuturesTimeout) as e:
                from ...server.serve import AdmissionError

                if isinstance(e, AdmissionError):
                    raise
                # Decode pod died/drained mid-handoff: re-plan ONLY the
                # decode hop — the prefill work is done and its chain is
                # still exportable; re-running ingest would waste it.
                exclude.add(decode_pod_name)
                if state["replans"] >= self.config.max_replans:
                    raise _HopFailed(decode_pod_name, "decode", e)
                state["replans"] += 1
                with self._mu:
                    self.replans += 1
                log.warning(
                    "decode hop failed mid-handoff; re-planning decode",
                    pod=decode_pod_name,
                    error=repr(e),
                )
                try:
                    replan = self.planner.plan(tokens, self._views_fn(), exclude)
                except PlanError:
                    raise _HopFailed(decode_pod_name, "decode", e)
                decode_pod_name = replan.decode_pod
                continue
            break
        t_decoded = time.monotonic()
        self.tracer.record_span(
            "disagg.handoff",
            span,
            t_handoff,
            min(
                dseq.prefill_start_time
                if dseq.prefill_start_time is not None
                else t_decoded,
                t_decoded,
            ),
            attrs={
                "prefill_pod": plan.prefill_pod,
                "decode_pod": decode_pod_name,
                "pull_source": pull_source,
                "chain_blocks": pseq.num_registered_pages,
            },
        )
        with self._mu:
            self.handoffs += 1
        combined = first + list(dseq.generated_tokens)
        # Mirror the HTTP handler's derivation: a trailing stop token is
        # "stop" even when it landed exactly at the max_new_tokens cap.
        stopped = combined[-1] in sampling.stop_token_ids
        reason = dseq.finish_reason or ("stop" if stopped else "length")
        return DisaggResult(
            tokens=combined,
            finish_reason=reason,
            mode="disagg",
            prefill_pod=plan.prefill_pod,
            decode_pod=decode_pod_name,
            replans=state["replans"],
            ttft_s=pseq.ttft,
            decode_cached_tokens=dseq.num_cached_prompt,
            handoff_s=(
                dseq.prefill_start_time - t_handoff
                if dseq.prefill_start_time is not None
                else None
            ),
        )

    def _submit_decode_hop(
        self, decode_pod, handoff_tokens, sampling, deadline, span,
        pull_source, prompt_len, tenant="",
    ):
        """Decode-tier admission: async-pull pods import the chain in the
        PR 7 ``importing`` state (admission never blocks on the wire);
        pods without the knob do the PR 2 blocking pull first — either
        way every pull failure degrades to cold prefill of the handoff
        prompt, never a failed request."""
        if pull_source is not None and not decode_pod.config.async_pull:
            decode_pod.pull_prefix(
                handoff_tokens[:prompt_len],
                pull_source,
                deadline=deadline,
                trace_ctx=span.context,
            )
            pull_source = None
        return decode_pod.submit(
            handoff_tokens,
            sampling,
            deadline_s=self._remaining(deadline),
            trace_ctx=span.context,
            route_action="pull" if pull_source is not None else None,
            pull_source=pull_source,
            tenant=tenant,
        )

    def stats(self) -> dict:
        with self._mu:
            return {
                "handoffs": self.handoffs,
                "single_pod_served": self.single_pod_served,
                "replans": self.replans,
            }


class _HopFailed(Exception):
    """Internal: one hop's pod failed in a re-plannable way (died or
    drained mid-flight) — never an admission shed, which must surface."""

    def __init__(self, pod: str, hop: str, cause: Exception):
        super().__init__(f"{hop} hop failed on {pod}: {cause!r}")
        self.pod = pod
        self.hop = hop
        self.cause = cause
