"""Token sampling: greedy / temperature / top-k / top-p, jit-compiled.

One fused function over the batch — sampling params are per-sequence arrays
so mixed strategies share a single compiled program (no per-request
recompiles, XLA-friendly static shapes). ``spec_sample`` extends the same
filtered distributions to speculative-decode verification with
DETERMINISTIC drafts (prompt-lookup proposals): accept draft ``d`` with
probability ``P(d)``; on rejection sample from the residual ``P`` with
``d`` removed (for a delta-function proposal the standard
speculative-sampling residual ``(p - q)_+`` is exactly that) — the emitted
stream is an exact sample of the target distribution per position.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _filtered_logits(
    logits: jnp.ndarray,  # [rows, vocab] f32
    temperature: jnp.ndarray,  # [rows] f32; 0 = greedy (filter inert)
    top_k: jnp.ndarray,  # [rows] int32; 0 = disabled
    top_p: jnp.ndarray,  # [rows] f32; 1 = disabled
) -> jnp.ndarray:
    """Temperature-scaled logits with top-k/top-p masking (-inf off-support)."""
    vocab = logits.shape[-1]

    # Temperature scaling (guard 0 for the greedy lanes).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # Top-k mask: keep the k highest logits per row.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [rows, vocab]
    k = jnp.where(top_k > 0, top_k, vocab).astype(jnp.int32)
    kth_val = jnp.take_along_axis(
        sorted_desc, jnp.clip(k - 1, 0, vocab - 1)[:, None], axis=-1
    )
    masked = jnp.where(scaled >= kth_val, scaled, -jnp.inf)

    # Top-p (nucleus) on the surviving distribution.
    sorted_masked = jnp.sort(masked, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative prob (exclusive) < top_p
    cutoff_mask = (cumprobs - probs_sorted) < top_p[:, None]
    threshold = jnp.min(
        jnp.where(cutoff_mask, sorted_masked, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(masked >= threshold, masked, -jnp.inf)


@functools.partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jnp.ndarray,  # [batch, vocab] f32
    temperature: jnp.ndarray,  # [batch] f32; 0 = greedy
    top_k: jnp.ndarray,  # [batch] int32; 0 = disabled
    top_p: jnp.ndarray,  # [batch] f32; 1 = disabled
    rng_key: jax.Array,
) -> jnp.ndarray:
    """Returns sampled token ids [batch] int32."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = _filtered_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(rng_key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


@functools.partial(jax.jit, static_argnames=())
def spec_sample(
    logits: jnp.ndarray,  # [batch, s, vocab] f32 — verify logits per position
    drafts: jnp.ndarray,  # [batch, s] int32 — proposed token per position
    temperature: jnp.ndarray,  # [batch] f32; 0 = greedy
    top_k: jnp.ndarray,  # [batch] int32
    top_p: jnp.ndarray,  # [batch] f32
    rng_key: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative verification for deterministic drafts.

    Per position ``j`` with filtered target distribution ``P_j``:

    - ``accept[b, j]``: draft accepted — sampled lanes with probability
      ``P_j(draft)``, greedy lanes iff ``draft == argmax``;
    - ``replacement[b, j]``: the token to emit at the FIRST rejection —
      sampled from ``P_j`` with the draft removed and renormalized (the
      ``(p - q)_+`` residual for a delta proposal; never equals the
      draft), greedy lanes the plain argmax;
    - ``free[b, j]``: an unconditioned sample from ``P_j`` — used for the
      bonus position after all drafts accept (and for empty-proposal
      lanes, where position 0 is a plain decode sample).

    The host walks accept[] to the first False per lane; everything after
    is discarded (those positions were scored under a rejected context).
    """
    b, s, vocab = logits.shape
    flat = logits.reshape(b * s, vocab)
    rep = lambda x: jnp.repeat(x, s)
    masked = _filtered_logits(flat, rep(temperature), rep(top_k), rep(top_p))
    greedy = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    d = drafts.reshape(-1).astype(jnp.int32)

    probs = jax.nn.softmax(masked, axis=-1)
    p_draft = jnp.take_along_axis(probs, d[:, None], axis=-1)[:, 0]

    k_u, k_repl, k_free = jax.random.split(rng_key, 3)
    u = jax.random.uniform(k_u, (b * s,))
    sampled_accept = u < p_draft
    accept = jnp.where(rep(temperature) > 0, sampled_accept, d == greedy)

    draft_hot = jax.nn.one_hot(d, vocab, dtype=bool)
    masked_no_draft = jnp.where(draft_hot, -jnp.inf, masked)
    repl_sampled = jax.random.categorical(k_repl, masked_no_draft, axis=-1)
    replacement = jnp.where(
        rep(temperature) > 0, repl_sampled, greedy
    ).astype(jnp.int32)

    free_sampled = jax.random.categorical(k_free, masked, axis=-1)
    free = jnp.where(rep(temperature) > 0, free_sampled, greedy).astype(
        jnp.int32
    )
    return (
        accept.reshape(b, s),
        replacement.reshape(b, s),
        free.reshape(b, s),
    )
