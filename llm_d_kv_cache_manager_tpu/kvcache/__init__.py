from . import kvblock  # noqa: F401
