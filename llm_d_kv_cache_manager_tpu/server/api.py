"""The online scoring service: HTTP API + full wiring.

Parity with the reference's shipped binary (``examples/kv_events/online/
main.go``): starts the KV-cache indexer, the event-ingestion pool with its
ZMQ subscriber, optional metrics, and serves:

- ``POST /score_completions``       {"prompt": str, "model": str,
                                     "pod_identifiers": [str]?}
- ``POST /score_chat_completions``  {"messages": [...], "model": str,
                                     "chat_template": str?, ...}
  (fetches + renders the model's chat template, then scores the flattened
  prompt — reference ``online/main.go:273-339``)
- ``GET  /metrics``                 Prometheus exposition
- ``GET  /healthz``

Configuration comes from env vars matching the reference's
(``online/main.go:162-209``): HF_TOKEN, BLOCK_SIZE, PYTHONHASHSEED,
ZMQ_ENDPOINT, ZMQ_TOPIC, POOL_CONCURRENCY, HTTP_PORT.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from aiohttp import web

from ..kvcache import KVCacheIndexer, KVCacheIndexerConfig
from ..kvcache.kvblock import TokenProcessorConfig
from ..kvcache.metrics import collector
from ..kvcache.kvevents import (
    FleetHealth,
    FleetHealthConfig,
    KVEventsPool,
    KVEventsPoolConfig,
    ZMQSubscriber,
    ZMQSubscriberConfig,
)
from ..obs.tracing import Tracer, format_traceparent, parse_traceparent
from ..preprocessing import ChatTemplatingProcessor, FetchTemplateRequest, RenderRequest
from ..tokenization import HFTokenizerConfig, TokenizationPoolConfig
from ..utils import get_logger, log_context

log = get_logger("server.api")


def _parse_placement(body: dict):
    """The optional ``placement`` field both scoring endpoints accept:
    returns ``(placement, None)`` when valid ("prefill"/"decode" for the
    disagg tiers, "pull_source" for the remote-tier read path — no role
    exclusion, liveness gate only, so kvstore holders are scorable as
    pull sources — or absent) or ``(None, 400-response)`` for anything
    else."""
    placement = body.get("placement")
    if placement not in (None, "prefill", "decode", "pull_source"):
        return None, web.json_response(
            {
                "error": "placement must be 'prefill', 'decode' or "
                "'pull_source' when set"
            },
            status=400,
        )
    return placement, None


@dataclass
class ServiceConfig:
    http_port: int = 8080
    zmq_endpoint: str = "tcp://*:5557"
    zmq_topic: str = "kv@"
    pool_concurrency: int = 4
    block_size: int = 16
    hash_seed: str = ""
    hf_token: Optional[str] = None
    enable_metrics: bool = True
    metrics_logging_interval: float = 0.0
    # Use the C++ index backend when its library is built (strictly faster,
    # same conformance-tested semantics); NATIVE_INDEX=0 forces pure Python.
    native_index: bool = True
    #: fleet self-healing: seconds of pod silence before its entries are
    #: swept from the index and it stops being scored. 0 (default) = off —
    #: observation-only health tracking, legacy routing behavior.
    pod_ttl_s: float = 0.0
    #: request tracing (PR 5): mint-or-adopt a W3C trace id per scoring
    #: request, record a ``scorer.score`` span, echo the ``traceparent``
    #: response header for the router to forward, and serve finished
    #: traces at ``GET /debug/traces``. Off (default) = no new headers,
    #: bit-identical responses.
    obs_tracing: bool = False
    #: finished-span ring size for /debug/traces
    obs_trace_buffer: int = 2048
    #: routing-quality audit (PR 10): index-staleness probes on event
    #: ingest (publish→visibility lag per pod/event type, events-behind
    #: per pod, ``/debug/staleness``) and the predicted-vs-realized route
    #: audit (scoring requests carrying a ``request_id`` record their
    #: scoreboard; pods report realized hits via ``RequestAudit`` events;
    #: joined audits at ``/debug/audit``). Off (default) = no trackers
    #: attached, bit-identical responses and ``/stats``.
    obs_audit: bool = False
    #: joined-audit ring size for /debug/audit
    obs_audit_ring: int = 2048
    #: scoring-side OBS_METRICS (PR 10 satellite): the
    #: ``kvcache_scorer_scoreboard_size`` / ``kvcache_index_events_behind``
    #: gauges and an ``obs`` block on ``/stats``. Off (default) keeps the
    #: legacy ``/stats`` field set.
    obs_metrics: bool = False
    #: KV-capacity observability (ISSUE 15): scorer-side block-lifecycle
    #: ledger fed from the KV-event stream the pool already decodes
    #: (``BlockStored``/``BlockRemoved`` with their medium — no new wire
    #: fields), surfaced at ``/debug/lifecycle``, a ``lifecycle`` /stats
    #: block, and the ``kvcache_block_tier_*`` metric families. Off
    #: (default) = no ledger attached, bit-identical responses/``/stats``.
    obs_lifecycle: bool = False
    #: lifecycle-ledger ring depth for /debug/lifecycle
    obs_lifecycle_ring: int = 4096
    #: sharded control plane (PR 11): partition the block index by chain
    #: hash across this many scorer shards — per-shard event-apply workers
    #: (no cross-shard lock on ingest) and score reads fanned out across
    #: shards and merged. 0 (default) = the single-index legacy plane,
    #: bit-identical responses, /stats fields, and wire bytes.
    scorer_shards: int = 0
    #: virtual nodes per shard on the consistent-hash ring (sizing: higher
    #: = smoother balance and smaller resize movement, more ring memory)
    scorer_shard_vnodes: int = 64
    #: predicted-TTFT routing (ISSUE 14): attach a ``TTFTPredictor`` to
    #: the scoring plane — scoring requests carrying a ``signals`` body
    #: field (per-pod queue depth / prefill rate from the caller's
    #: serving telemetry) get a ``predicted_ttft_s`` map alongside the
    #: scores, so an EPP-style router can argmin on modeled latency.
    #: The corrector loop needs a realized-TTFT feed, which only
    #: IN-PROCESS callers have (``RouteAuditor.record_realized(...,
    #: realized_ttft_s=)``; the ``RequestAudit`` wire event carries
    #: blocks, not latency — no new wire fields): an HTTP-only
    #: deployment serves uncorrected model output and its /stats
    #: ``predict.corrector`` stays at bias 1.0. Off (default) = no new
    #: body fields read, bit-identical responses and ``/stats``.
    route_predict: bool = False
    #: the fleet's heartbeat cadence for the predictor's staleness gate:
    #: a pod whose last heartbeat is older than 2x this treats its
    #: queue/rate signals as unknown (conservative defaults). 0 = the
    #: staleness gate is off (signals trusted as supplied)
    route_predict_heartbeat_s: float = 0.0
    #: fleet observability federation (ISSUE 20): attach a scorer-side
    #: ``FleetFederator`` that polls every registered pod's ``/stats`` +
    #: ``/debug/*`` surfaces (in-process hooks or HTTP) and serves the
    #: joined, causally-stamped fleet snapshot at ``GET /debug/fleet``
    #: plus a ``fed`` /stats block and the ``kvcache_fleet_*`` scrape
    #: families. Off (default) = no federator attached, bit-identical
    #: ``/stats`` keys, exposition, and wire bytes.
    obs_fed: bool = False
    #: federation delta-ring depth (scrapes of history) for /debug/fleet
    obs_fed_ring: int = 256
    #: per-pod HTTP fetch timeout for federated scrapes, seconds (the
    #: in-process hook path never times out)
    obs_fed_timeout_s: float = 2.0
    #: OpenMetrics trace exemplars (ISSUE 20): the scorer's score-latency
    #: histogram attaches the observing request's trace_id per bucket and
    #: ``/metrics`` switches to the OpenMetrics exposition (the classic
    #: text format drops exemplars). Off (default) = classic exposition,
    #: bit-identical bytes.
    obs_exemplars: bool = False

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        env = os.environ
        return cls(
            http_port=int(env.get("HTTP_PORT", "8080")),
            zmq_endpoint=env.get("ZMQ_ENDPOINT", "tcp://*:5557"),
            zmq_topic=env.get("ZMQ_TOPIC", "kv@"),
            pool_concurrency=int(env.get("POOL_CONCURRENCY", "4")),
            block_size=int(env.get("BLOCK_SIZE", "16")),
            hash_seed=env.get("PYTHONHASHSEED", ""),
            hf_token=env.get("HF_TOKEN") or None,
            enable_metrics=env.get("ENABLE_METRICS", "true").lower() != "false",
            metrics_logging_interval=float(env.get("METRICS_LOGGING_INTERVAL", "0")),
            native_index=env.get("NATIVE_INDEX", "1").lower() not in ("0", "false"),
            pod_ttl_s=float(env.get("POD_TTL_S", "0")),
            obs_tracing=env.get("OBS_TRACING", "").strip().lower()
            in ("1", "true", "yes", "on"),
            obs_trace_buffer=int(env.get("OBS_TRACE_BUFFER", "2048")),
            obs_audit=env.get("OBS_AUDIT", "").strip().lower()
            in ("1", "true", "yes", "on"),
            obs_audit_ring=int(env.get("OBS_AUDIT_RING", "2048")),
            obs_metrics=env.get("OBS_METRICS", "").strip().lower()
            in ("1", "true", "yes", "on"),
            obs_lifecycle=env.get("OBS_LIFECYCLE", "").strip().lower()
            in ("1", "true", "yes", "on"),
            obs_lifecycle_ring=int(env.get("OBS_LIFECYCLE_RING", "4096")),
            scorer_shards=int(env.get("SCORER_SHARDS", "0")),
            scorer_shard_vnodes=int(env.get("SCORER_SHARD_VNODES", "64")),
            route_predict=env.get("ROUTE_PREDICT", "").strip().lower()
            in ("1", "true", "yes", "on"),
            route_predict_heartbeat_s=float(
                env.get("ROUTE_PREDICT_HEARTBEAT_S", "0")
            ),
            obs_fed=env.get("OBS_FED", "").strip().lower()
            in ("1", "true", "yes", "on"),
            obs_fed_ring=int(env.get("OBS_FED_RING", "256")),
            obs_fed_timeout_s=float(env.get("OBS_FED_TIMEOUT_S", "2.0")),
            obs_exemplars=env.get("OBS_EXEMPLARS", "").strip().lower()
            in ("1", "true", "yes", "on"),
        )


class ScoringService:
    """Owns the indexer + event plane and exposes the HTTP handlers."""

    @staticmethod
    def _index_config(cfg: "ServiceConfig"):
        from ..kvcache.kvblock import (
            IndexConfig,
            NativeMemoryIndexConfig,
            native_available,
        )

        use_native = cfg.native_index and native_available()
        if cfg.native_index and not use_native:
            log.warning(
                "native index requested but liblruindex.so is not built — "
                "falling back to the pure-Python index (~4x slower hot RPC); "
                "run `python -m llm_d_kv_cache_manager_tpu.native.build`"
            )
        else:
            log.info(
                "index backend selected",
                backend="native" if use_native else "in_memory",
            )
        return IndexConfig(
            native_memory=NativeMemoryIndexConfig() if use_native else None,
            in_memory=None if use_native else IndexConfig().in_memory,
            enable_metrics=cfg.enable_metrics,
            metrics_logging_interval=cfg.metrics_logging_interval,
        )

    def _build_sharded_index(self, cfg: "ServiceConfig"):
        """SCORER_SHARDS plane: N independent backend sub-indexes behind
        the chain-hash facade. Metrics instrumentation wraps the FACADE
        (one logical read = one lookup metric, as on a single index); the
        events plane accounts its applies itself."""
        import dataclasses

        from ..kvcache.kvblock import InstrumentedIndex, create_index
        from ..kvcache.sharding import ShardedIndex

        base = self._index_config(cfg)
        shard_cfg = dataclasses.replace(
            base, enable_metrics=False, metrics_logging_interval=0.0
        )
        if shard_cfg.native_memory is not None:
            # Native shards share ONE intern table, which is what lets the
            # facade serve score fan-outs in a single C call (shared locks
            # inside, no Python lock).
            from ..kvcache.kvblock.native_memory import NativeMemoryIndex

            shards = NativeMemoryIndex.shard_group(
                cfg.scorer_shards, shard_cfg.native_memory
            )
        else:
            shards = [create_index(shard_cfg) for _ in range(cfg.scorer_shards)]
        self.sharded_index = ShardedIndex(
            shards, vnodes=cfg.scorer_shard_vnodes
        )
        log.info(
            "sharded control plane enabled",
            shards=cfg.scorer_shards,
            vnodes=cfg.scorer_shard_vnodes,
        )
        index = self.sharded_index
        if cfg.enable_metrics:
            collector.register()
            index = InstrumentedIndex(index)
            if cfg.metrics_logging_interval > 0:
                collector.start_metrics_logging(cfg.metrics_logging_interval)
        return index

    def __init__(
        self,
        config: Optional[ServiceConfig] = None,
        *,
        tokenizer=None,
        on_bad_block=None,
    ):
        """``on_bad_block`` (optional, ``fn(holder, block_hashes,
        medium)``): the fleet-revocation purge hook, threaded into the
        events pool. In-process fleet harnesses wire it to every pod's
        ``purge_bad_blocks`` so a ``BadBlock`` from one holder also
        destroys replica copies peers still store; a networked deployment
        leaves it None (index revocation here is what unroutes the bytes,
        and each holder quarantines its own copy at verify time)."""
        self.config = config or ServiceConfig()
        self._on_bad_block = on_bad_block
        cfg = self.config

        # Fleet health is always attached (observation is free); expiry +
        # sweeping only activate when POD_TTL_S > 0.
        self.fleet_health = FleetHealth(FleetHealthConfig(pod_ttl_s=cfg.pod_ttl_s))
        #: SCORER_SHARDS: the raw chain-hash-partitioned facade (None on
        #: the legacy single-index plane). The indexer may additionally see
        #: it through the instrumented decorator; the events plane applies
        #: to the raw sub-indexes.
        self.sharded_index = None
        #: last scrape's per-shard occupancy (written by the gauge refresh,
        #: read by the /stats sharding block — one walk per scrape)
        self._last_shard_sizes = None
        index = None
        if cfg.scorer_shards > 0:
            index = self._build_sharded_index(cfg)
        self.indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(
                    block_size=cfg.block_size, hash_seed=cfg.hash_seed
                ),
                index=self._index_config(cfg),
                tokenization_pool=TokenizationPoolConfig(
                    hf_tokenizer=HFTokenizerConfig(huggingface_token=cfg.hf_token)
                ),
            ),
            index=index,
            tokenizer=tokenizer,
            fleet_health=self.fleet_health,
        )
        #: routing-quality observability (OBS_AUDIT / OBS_METRICS): the
        #: staleness tracker rides event ingest whenever either surface
        #: wants it (events-behind needs the seq high-waters); the route
        #: auditor only with the audit knob. None (default) = the pool
        #: runs bit-identical legacy. Under SCORER_SHARDS each shard lane
        #: gets its own tracker (shard-labeled gauges) and ``staleness``
        #: becomes the merged read view over them.
        from ..obs.audit import MergedStaleness, RouteAuditor, StalenessTracker

        self._shard_staleness = None
        if cfg.obs_audit or cfg.obs_metrics:
            if self.sharded_index is not None:
                self._shard_staleness = [
                    StalenessTracker(shard=str(i))
                    for i in range(cfg.scorer_shards)
                ]
                self.staleness = MergedStaleness(self._shard_staleness)
            else:
                self.staleness = StalenessTracker()
        else:
            self.staleness = None
        #: scorer-side block-lifecycle ledger (OBS_LIFECYCLE): fed from
        #: the event stream (the pool's BlockStored/BlockRemoved feed),
        #: metric callbacks into the global collector registry. None
        #: (default) = the pool runs bit-identical legacy.
        self.lifecycle = None
        if cfg.obs_lifecycle:
            from ..obs.lifecycle import BlockLifecycleLedger

            self.lifecycle = BlockLifecycleLedger(
                ring=cfg.obs_lifecycle_ring,
                on_transition=collector.observe_tier_transition,
                on_residency=collector.observe_tier_residency,
            )
            # A TTL-swept pod must leave the ledger too (PodDrained and
            # resync wipes are fed by the pools; the sweeper bypasses
            # them and talks straight to the index).
            self.fleet_health.on_pod_swept = (
                lambda pod: self.lifecycle.observe_pod_gone(pod, "ttl_swept")
            )
        #: fleet miss-ratio-curve registry: per-pod ``/debug/mrc``
        #: payloads, pushed by pods (POST /debug/mrc) or an in-process
        #: fleet harness, aggregated on read into the ONE fleet curve the
        #: fleet controller scales on. Plain dict + lock, no knob: an
        #: empty registry answers disabled-shaped, same as a pod with
        #: OBS_LIFECYCLE off — nothing changes until somebody reports.
        self._pod_mrc: dict[str, dict] = {}  # guarded_by: _pod_mrc_mu
        self._pod_mrc_mu = threading.Lock()
        #: predicted-TTFT routing (ROUTE_PREDICT): the latency model +
        #: per-pod corrector. None (default) = no predictor, no new body
        #: fields read, bit-identical responses and /stats.
        if cfg.route_predict:
            from ..kvcache.predictor import TTFTPredictor, TTFTPredictorConfig

            self.predictor = TTFTPredictor(
                TTFTPredictorConfig(
                    block_size=cfg.block_size,
                    heartbeat_interval_s=cfg.route_predict_heartbeat_s,
                )
            )
        else:
            self.predictor = None
        self.route_auditor = (
            RouteAuditor(
                index=self.indexer.kv_block_index,
                fleet_health=self.fleet_health,
                ring=cfg.obs_audit_ring,
                # The audit plane as an actuator: joins carrying realized
                # TTFT correct the routing model's per-pod bias.
                ttft_corrector=(
                    self.predictor.corrector
                    if self.predictor is not None
                    else None
                ),
            )
            if cfg.obs_audit
            else None
        )
        if self.sharded_index is not None:
            from ..kvcache.sharding import (
                ShardedEventsPool,
                ShardedEventsPoolConfig,
            )

            self.events_pool = ShardedEventsPool(
                self.sharded_index,
                ShardedEventsPoolConfig(dispatchers=cfg.pool_concurrency),
                health=self.fleet_health,
                staleness=self._shard_staleness,
                audit=self.route_auditor,
                lifecycle=self.lifecycle,
                instrument=cfg.enable_metrics,
                on_bad_block=self._on_bad_block,
            )
            if isinstance(self.staleness, MergedStaleness):
                # Fold the plane's admission-edge backlog (batches queued
                # ahead of decode) into the events-behind view — per-shard
                # lane trackers only see work after dispatch.
                self.staleness.admission = self.events_pool.admission_behind
        else:
            self.events_pool = KVEventsPool(
                self.indexer.kv_block_index,
                KVEventsPoolConfig(concurrency=cfg.pool_concurrency),
                health=self.fleet_health,
                staleness=self.staleness,
                audit=self.route_auditor,
                lifecycle=self.lifecycle,
                on_bad_block=self._on_bad_block,
            )
        self.subscriber = ZMQSubscriber(
            self.events_pool,
            ZMQSubscriberConfig(endpoint=cfg.zmq_endpoint, topic_filter=cfg.zmq_topic),
        )
        self.chat = ChatTemplatingProcessor()
        #: last scoring response's scoreboard size (OBS_METRICS gauge echo)
        self._last_scoreboard_size = 0
        #: request tracing (OBS_TRACING; a disabled tracer is free)
        self.tracer = Tracer(
            enabled=cfg.obs_tracing,
            max_spans=cfg.obs_trace_buffer,
            service="scorer",
        )
        #: fleet observability federation (OBS_FED): the scorer-side
        #: scrape-and-join over every registered pod's surfaces. None
        #: (default) = no federator, /debug/fleet answers disabled-shaped,
        #: bit-identical /stats keys and exposition.
        self.federator = None
        if cfg.obs_fed:
            from ..obs.federation import FleetFederator

            self.federator = FleetFederator(
                health=self.fleet_health,
                staleness=self.staleness,
                ring=cfg.obs_fed_ring,
                timeout_s=cfg.obs_fed_timeout_s,
                on_scrape=collector.observe_fleet_scrape,
            )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.chat.initialize()
        self.indexer.run()
        self.events_pool.start()
        self.subscriber.start()
        self.fleet_health.start_sweeper(self.indexer.kv_block_index)
        log.info(
            "scoring service started",
            zmq=self.config.zmq_endpoint,
            block_size=self.config.block_size,
            pod_ttl_s=self.config.pod_ttl_s,
        )

    def shutdown(self) -> None:
        self.fleet_health.stop_sweeper()
        self.subscriber.shutdown()
        self.events_pool.shutdown()
        self.indexer.shutdown()
        self.chat.finalize()

    # -- handlers -----------------------------------------------------------
    async def handle_score_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        prompt = body.get("prompt")
        model = body.get("model")
        if not isinstance(prompt, str) or not isinstance(model, str) or not model:
            return web.json_response(
                {"error": "fields 'prompt' (str) and 'model' (str) are required"},
                status=400,
            )
        pods = body.get("pod_identifiers") or []
        placement, bad = _parse_placement(body)
        if bad is not None:
            return bad
        headers, scores, degraded, predicted = await self._traced_score(
            request, "/score_completions", prompt, model, pods, placement,
            request_id=self._audit_request_id(body),
            signals=self._parse_signals(body, placement, pods),
        )
        if degraded is not None:
            return web.json_response(
                {"scores": {}, "degraded": degraded}, headers=headers
            )
        return web.json_response(
            {
                "scores": scores,
                # Key appears only under ROUTE_PREDICT with signals
                # supplied: knobs-off responses keep their legacy keys.
                **(
                    {"predicted_ttft_s": predicted}
                    if predicted is not None
                    else {}
                ),
            },
            headers=headers,
        )

    def _audit_request_id(self, body: dict) -> Optional[str]:
        """The optional ``request_id`` scoring-body field, read ONLY with
        the audit knob on — the knobs-off request path inspects no body
        fields it didn't before."""
        if self.route_auditor is None:
            return None
        rid = body.get("request_id")
        return rid if isinstance(rid, str) and rid else None

    def _parse_signals(self, body: dict, placement=None, candidates=None):
        """The optional ``signals`` scoring-body field (ROUTE_PREDICT):
        ``[{"pod": str, "queue_depth": num?, "prefill_rate": num?}, ...]``
        — the caller's serving-plane telemetry, merged with the
        heartbeat-derived half (signal age, draining/expired, role) from
        fleet health. Read ONLY with the predict knob on; malformed rows
        are skipped (a bad signal must not fail scoring). Rows naming
        pods outside ``candidates`` (the request's ``pod_identifiers``,
        when given) or whose advertised role cannot serve ``placement``
        are dropped — ``predicted_ttft_s`` must never steer the caller
        toward a pod the scoreboard's own filters would have rejected."""
        if self.predictor is None:
            return None
        raw = body.get("signals")
        if not isinstance(raw, list) or not raw:
            return None
        from ..kvcache.predictor import PodSignals

        allowed = set(candidates) if candidates else None
        # Same role gate as FleetHealth.filter_scores (kvstore is
        # excluded by the predictor itself; "pull_source" has no gate).
        wrong_role = {
            "prefill": {"decode"},
            "decode": {"prefill"},
        }.get(placement, set())
        # Scope the fleet-health cut to the pods this request names —
        # an O(fleet) locked walk per scoring request would scale with
        # fleet size, not request size.
        named = [
            row["pod"]
            for row in raw
            if isinstance(row, dict) and isinstance(row.get("pod"), str)
        ]
        views = self.indexer.signal_views(named)
        sigs = []
        for row in raw:
            if not isinstance(row, dict) or not isinstance(
                row.get("pod"), str
            ):
                continue
            if allowed is not None and row["pod"] not in allowed:
                continue
            view = views.get(row["pod"], {})
            if view.get("role") in wrong_role:
                continue
            qd = row.get("queue_depth")
            pr = row.get("prefill_rate")
            sigs.append(
                PodSignals(
                    name=row["pod"],
                    queue_depth=(
                        float(qd)
                        if isinstance(qd, (int, float))
                        and not isinstance(qd, bool)
                        else None
                    ),
                    prefill_rate=(
                        float(pr)
                        if isinstance(pr, (int, float))
                        and not isinstance(pr, bool)
                        and pr > 0
                        else None
                    ),
                    draining=bool(view.get("draining", False)),
                    dead=bool(view.get("expired", False)),
                    role=view.get("role"),
                    signal_age_s=view.get("age_s"),
                )
            )
        return sigs or None

    async def _traced_score(
        self,
        request: web.Request,
        endpoint: str,
        prompt: str,
        model: str,
        pods,
        placement=None,
        request_id: Optional[str] = None,
        signals=None,
    ):
        """The one scoring path both endpoints share: trace mint-or-adopt
        (the scoring service is the fleet's front door, so the trace id
        established here is the one the router forwards to the serving pod
        and the pod to its transfer peer), score off the event loop, score
        latency + degradation accounting. Returns ``(headers, scores,
        degraded)`` — ``degraded`` is the error string when the index
        backend failed: degrade to an empty scoreboard so the router falls
        back to a cold placement and the REQUEST still serves, just
        without cache affinity (a 500 here would turn an index outage
        into a serving outage). ``placement`` ("prefill"/"decode"/None)
        is the disagg tier being placed for — pods whose advertised role
        cannot serve it are dropped from the scoreboard.

        ``signals`` (ROUTE_PREDICT, parsed ``PodSignals``): the modeled
        per-pod TTFT rides back as the fourth tuple element so an
        external router can argmin on latency instead of score-max —
        None everywhere else, and the response then carries no new key.
        Returns ``(headers, scores, degraded, predicted_ttft)``."""
        loop = asyncio.get_running_loop()
        span = self.tracer.start_span(
            "scorer.score",
            parent=parse_traceparent(request.headers.get("traceparent"))
            if self.tracer.enabled
            else None,
            attrs={"endpoint": endpoint, "model": model},
        )
        headers = (
            {"traceparent": format_traceparent(span.context)}
            if span.context is not None
            else None
        )
        with span, log_context(
            trace_id=span.context.trace_id if span.context else None
        ):
            t0 = time.perf_counter()
            try:
                if signals is not None and self.predictor is not None:
                    # The predict path tokenizes once and scores the
                    # token ids directly (the pool's prefix store makes
                    # the split free), because the latency model needs
                    # the prompt's token length for its miss term.
                    def score_with_len():
                        toks = self.indexer.tokenization_pool.tokenize(
                            prompt, model
                        )
                        return (
                            self.indexer.score_tokens(
                                toks, model, pods, placement
                            ),
                            len(toks),
                        )

                    scores, prompt_len = await loop.run_in_executor(
                        None, score_with_len
                    )
                else:
                    scores = await loop.run_in_executor(
                        None, self.indexer.get_pod_scores, prompt, model,
                        pods, placement,
                    )
                    prompt_len = None
            except Exception as exc:
                log.exception("scoring failed; degrading to empty scoreboard")
                collector.bump("scorer_errors")
                collector.scorer_errors.inc()
                span.set_attr("error", type(exc).__name__)
                return headers, None, str(exc), None
            collector.observe_score_latency(
                time.perf_counter() - t0,
                # OBS_EXEMPLARS: the observing request's trace id rides
                # the histogram bucket as an OpenMetrics exemplar.
                trace_id=(
                    span.context.trace_id
                    if self.config.obs_exemplars and span.context is not None
                    else None
                ),
            )
            span.set_attr("pods_scored", len(scores))
            if self.config.obs_metrics:
                collector.set_scoreboard_size(len(scores))
                self._last_scoreboard_size = len(scores)
            predicted = None
            if (
                signals is not None
                and self.predictor is not None
                and prompt_len
            ):
                arms = self.predictor.predict_routes(
                    signals, prompt_len, scores
                )
                if arms:
                    predicted = {
                        p: round(a.ttft_s, 6)
                        for p, a in arms.items()
                        if a.ttft_s != float("inf")
                    }
                    if predicted:
                        collector.observe_predicted_ttft(
                            min(predicted.values())
                        )
            if self.route_auditor is not None and request_id is not None:
                # The scorer's half of the audit: the scoreboard this
                # request saw, with the argmax pod standing in for the
                # caller's eventual pick (the HTTP deployment's router is
                # external; an in-process BlendedRouter records richer
                # decisions itself) — under ROUTE_PREDICT the stand-in
                # is the latency argmin, the pod the caller will pick.
                # Empty scoreboard = an honest cold prediction of 0
                # blocks.
                if predicted:
                    chosen = min(
                        predicted, key=lambda p: (predicted[p], p)
                    )
                else:
                    chosen = (
                        max(scores, key=lambda p: (scores[p], p))
                        if scores
                        else ""
                    )
                predicted_ttft_chosen = None
                if predicted and chosen in predicted:
                    predicted_ttft_chosen = predicted[chosen]
                self.route_auditor.record_decision(
                    request_id,
                    chosen_pod=chosen,
                    predicted_blocks=scores.get(chosen, 0),
                    index_blocks=scores.get(chosen, 0),
                    scoreboard=scores,
                    model=model,
                    trace_id=(
                        span.context.trace_id
                        if span.context is not None
                        else None
                    ),
                    predicted_ttft_s=predicted_ttft_chosen,
                )
        return headers, scores, None, predicted

    async def handle_score_chat_completions(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        messages = body.get("messages")
        model = body.get("model")
        if not isinstance(messages, list) or not messages or not model:
            return web.json_response(
                {"error": "fields 'messages' (list) and 'model' (str) are required"},
                status=400,
            )
        # Validate before the template render: an invalid placement is a
        # guaranteed 400 and must not pay the fetch+render first.
        placement, bad = _parse_placement(body)
        if bad is not None:
            return bad
        loop = asyncio.get_running_loop()

        def render():
            template, template_vars = self.chat.fetch_chat_template(
                FetchTemplateRequest(
                    model=model,
                    chat_template=body.get("chat_template"),
                    token=self.config.hf_token,
                )
            )
            rendered = self.chat.render_chat_template(
                RenderRequest(
                    conversations=[messages],
                    chat_template=template,
                    tools=body.get("tools"),
                    add_generation_prompt=body.get("add_generation_prompt", True),
                    continue_final_message=body.get("continue_final_message", False),
                    template_vars=template_vars,
                )
            )
            return rendered.rendered_chats[0]

        # Template fetch/render failures are deterministic request problems
        # (malformed messages, bad chat_template) — a 400 the client can
        # act on, NOT a degradation: masking them as empty scores would
        # cold-place the broken request forever and pollute the
        # scorer-error counter that alerts on index outages.
        try:
            prompt = await loop.run_in_executor(None, render)
        except Exception as exc:
            log.exception("chat template render failed")
            return web.json_response({"error": str(exc)}, status=400)
        headers, scores, degraded, predicted = await self._traced_score(
            request, "/score_chat_completions", prompt, model,
            body.get("pod_identifiers") or [], placement,
            request_id=self._audit_request_id(body),
            signals=self._parse_signals(
                body, placement, body.get("pod_identifiers") or []
            ),
        )
        if degraded is not None:
            # Index backend down: same degradation contract as
            # /score_completions — cost cache affinity, not the request.
            return web.json_response(
                {"scores": {}, "degraded": degraded}, headers=headers
            )
        return web.json_response(
            {
                "scores": scores,
                "rendered_prompt_chars": len(prompt),
                **(
                    {"predicted_ttft_s": predicted}
                    if predicted is not None
                    else {}
                ),
            },
            headers=headers,
        )

    def _refresh_index_gauges(self) -> Optional[dict]:
        """Scrape-driven index-occupancy snapshot: updates the
        ``kvcache_index_blocks`` / ``kvcache_index_pods`` gauges and
        returns the raw dict for /stats (None when the backend cannot
        answer cheaply, e.g. Redis). The walk is O(index keys) — callers
        on the event loop must push it to the executor."""
        try:
            if self.sharded_index is not None:
                # ONE per-shard walk per scrape feeds everything: the
                # shard-labeled gauges (where the keys actually live), the
                # truthful aggregate (blocks summed over disjoint ranges,
                # pods unioned), and the /stats sharding block (which
                # reads the stashed snapshot instead of re-walking).
                per = self.sharded_index.per_shard_size_info()
                self._last_shard_sizes = per
                for i, p in enumerate(per):
                    if p is not None:
                        collector.set_shard_index_size(
                            str(i), p["blocks"], p["pods"]
                        )
                if any(p is None for p in per):
                    info = None
                else:
                    names = self.sharded_index.pod_names()
                    info = {
                        "blocks": sum(p["blocks"] for p in per),
                        "pods": (
                            len(names)
                            if names is not None
                            else max((p["pods"] for p in per), default=0)
                        ),
                    }
            else:
                info = self.indexer.kv_block_index.size_info()
        except Exception:
            log.exception("index size_info failed")
            return None
        if info is not None:
            collector.set_index_size(info["blocks"], info["pods"])
        return info

    async def handle_metrics(self, request: web.Request) -> web.Response:
        await asyncio.get_running_loop().run_in_executor(
            None, self._refresh_index_gauges
        )
        try:
            import prometheus_client

            if self.config.obs_exemplars:
                # Exemplars render only in the OpenMetrics exposition —
                # the classic text format silently drops them. aiohttp's
                # content_type= rejects parameterized types, so the full
                # header rides the headers dict.
                from prometheus_client.openmetrics import exposition as om

                return web.Response(
                    body=om.generate_latest(prometheus_client.REGISTRY),
                    headers={"Content-Type": om.CONTENT_TYPE_LATEST},
                )
            data = prometheus_client.generate_latest()
            return web.Response(
                body=data, content_type="text/plain", charset="utf-8"
            )
        except ImportError:
            from ..kvcache.metrics import collector

            return web.json_response(collector.snapshot())

    async def handle_healthz(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def handle_stats(self, request: web.Request) -> web.Response:
        """Self-healing observability: per-pod health + stream-integrity
        counters (gaps/resyncs/sweeps/drops), subscriber drop counts, and
        the index collector's shadow counters."""
        from ..kvcache.metrics import collector

        # Occupancy first (off the event loop — O(index keys) walk), so the
        # snapshot below carries the fresh index_blocks/index_pods shadow
        # values too.
        index_size = await asyncio.get_running_loop().run_in_executor(
            None, self._refresh_index_gauges
        )
        payload = {
            "fleet": self.fleet_health.snapshot(),
            "subscriber": {
                "malformed_dropped": dict(self.subscriber.malformed_dropped),
            },
            "events_rejected_after_shutdown": (
                self.events_pool.rejected_after_shutdown
            ),
            "index_size": index_size,
            "index": collector.snapshot(),
        }
        # New blocks only behind their knobs: the knobs-off /stats payload
        # keeps its legacy field set bit-identical. The staleness tracker
        # is snapshotted ONCE and shared by the obs + staleness blocks —
        # two separate reads (the pre-ISSUE-20 shape) could tear: an event
        # applied between them made the obs block's events-behind disagree
        # with the staleness block's in the same response.
        stale_snap = (
            self.staleness.snapshot() if self.staleness is not None else None
        )
        if self.config.obs_metrics:
            payload["obs"] = {
                "scoreboard_size": self._last_scoreboard_size,
                "events_behind": (
                    stale_snap["events_behind"]
                    if stale_snap is not None
                    else {}
                ),
            }
        if stale_snap is not None and self.config.obs_audit:
            payload["staleness"] = stale_snap
        if self.lifecycle is not None:
            # Gated on OBS_LIFECYCLE: the knobs-off /stats payload keeps
            # its legacy field set bit-identical.
            payload["lifecycle"] = self.lifecycle.snapshot()
        if self.route_auditor is not None:
            payload["audit"] = self.route_auditor.snapshot()
        if self.predictor is not None:
            # Gated on ROUTE_PREDICT: the latency model's honesty
            # surface — prediction/abstention counts and the per-pod
            # corrector biases the audit joins have learned.
            payload["predict"] = self.predictor.snapshot()
        if self.sharded_index is not None:
            # Gated on SCORER_SHARDS: the knobs-off /stats payload keeps
            # its legacy field set bit-identical. The per-shard occupancy
            # is the snapshot the gauge refresh above just walked — one
            # O(shards) walk per scrape, not two.
            payload["sharding"] = {
                "shards": self.sharded_index.n_shards,
                "vnodes": self.sharded_index.ring.vnodes,
                "misroutes": self.events_pool.misroute_snapshot(),
                "per_shard_index": self._last_shard_sizes,
            }
        if self.federator is not None:
            # Gated on OBS_FED: compact scrape accounting only — the full
            # fleet join is /debug/fleet's job.
            payload["fed"] = self.federator.snapshot()
        return web.json_response(payload)

    async def handle_debug_traces(self, request: web.Request) -> web.Response:
        from ..obs.tracing import debug_traces_payload

        status, payload = debug_traces_payload(self.tracer, request.query)
        return web.json_response(payload, status=status)

    async def handle_debug_staleness(self, request: web.Request) -> web.Response:
        """Per-(pod, event type) publish→visibility histograms + the
        events-behind gauge state. Reports itself disabled (like
        /debug/traces) until OBS_AUDIT/OBS_METRICS attaches the tracker."""
        from ..obs.audit import debug_staleness_payload

        status, payload = debug_staleness_payload(
            self.staleness, request.query
        )
        return web.json_response(payload, status=status)

    async def handle_debug_audit(self, request: web.Request) -> web.Response:
        """Recent joined predicted-vs-realized audits, filterable by
        ``?request_id=`` / ``?trace_id=``; disabled until OBS_AUDIT."""
        from ..obs.audit import debug_audit_payload

        status, payload = debug_audit_payload(self.route_auditor, request.query)
        return web.json_response(payload, status=status)

    async def handle_debug_lifecycle(self, request: web.Request) -> web.Response:
        """The fleet's block tier story as seen from the event stream:
        recent per-pod transitions, filterable by ``?chain=``/``?block=``
        hash; disabled until OBS_LIFECYCLE."""
        from ..obs.lifecycle import debug_lifecycle_payload

        status, payload = debug_lifecycle_payload(self.lifecycle, request.query)
        return web.json_response(payload, status=status)

    # -- fleet miss-ratio curve (the autoscaler's capacity signal) ----------
    def report_mrc(self, pod: str, payload: Optional[dict]) -> None:
        """Register one pod's ``/debug/mrc`` payload (None drops the pod
        from the aggregate — a retired pod's stale curve must not keep
        voting). Called by the POST handler and by in-process fleet
        harnesses directly."""
        with self._pod_mrc_mu:
            if payload is None:
                self._pod_mrc.pop(pod, None)
            else:
                self._pod_mrc[pod] = payload

    def fleet_mrc(self) -> dict:
        """The fleet-aggregated miss-ratio curve: per-pod sampled curves
        merged sampled-weighted (aggregate == per-pod sum of sampled hits
        over summed samples — pinned by test)."""
        from ..kvcache.controller.mrc import aggregate_mrc

        with self._pod_mrc_mu:
            per_pod = dict(self._pod_mrc)
        return aggregate_mrc(per_pod)

    async def handle_debug_mrc(self, request: web.Request) -> web.Response:
        """GET: the fleet curve (disabled-shaped until any pod reports).
        POST: ``{"pod": ..., "mrc": {...}}`` registers a pod's curve
        (``"mrc": null`` withdraws it)."""
        if request.method == "POST":
            try:
                body = await request.json()
                pod = body["pod"]
                mrc = body.get("mrc")
                if not isinstance(pod, str) or not (
                    mrc is None or isinstance(mrc, dict)
                ):
                    raise TypeError
            except Exception:
                return web.json_response(
                    {"error": "want {'pod': str, 'mrc': dict|null}"},
                    status=400,
                )
            self.report_mrc(pod, mrc)
            return web.json_response({"ok": True})
        # The Tracer limit contract on the GET side: ?limit= caps fleet
        # curve rows (limit<=0 returns nothing), tolerant 400 on junk.
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            return web.json_response(
                {"error": "invalid limit (want an int)"}, status=400
            )
        payload = self.fleet_mrc()
        if "curve" in payload:
            payload["curve"] = payload["curve"][: max(limit, 0)]
        return web.json_response(payload)

    async def handle_debug_fleet(self, request: web.Request) -> web.Response:
        """The federated fleet snapshot: a FRESH scrape-and-join over
        every registered pod (pushed to an executor — the HTTP fetch path
        blocks) plus the delta-ring history; disabled-shaped until
        OBS_FED attaches the federator."""
        from ..obs.federation import debug_fleet_payload

        status, payload = await asyncio.get_running_loop().run_in_executor(
            None, debug_fleet_payload, self.federator, request.query
        )
        return web.json_response(payload, status=status)

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/score_completions", self.handle_score_completions)
        app.router.add_post("/score_chat_completions", self.handle_score_chat_completions)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/healthz", self.handle_healthz)
        app.router.add_get("/stats", self.handle_stats)
        app.router.add_get("/debug/traces", self.handle_debug_traces)
        app.router.add_get("/debug/staleness", self.handle_debug_staleness)
        app.router.add_get("/debug/audit", self.handle_debug_audit)
        app.router.add_get("/debug/lifecycle", self.handle_debug_lifecycle)
        app.router.add_get("/debug/mrc", self.handle_debug_mrc)
        app.router.add_post("/debug/mrc", self.handle_debug_mrc)
        app.router.add_get("/debug/fleet", self.handle_debug_fleet)
        return app


def main() -> None:
    config = ServiceConfig.from_env()
    service = ScoringService(config)
    service.start()
    app = service.build_app()

    async def on_shutdown(_app):
        service.shutdown()

    app.on_shutdown.append(on_shutdown)
    web.run_app(app, port=config.http_port)


if __name__ == "__main__":
    main()
