"""Disaggregated prefill/decode serving (ISSUE 9).

The acceptance pins of the subsystem:

- ``POD_ROLE`` unset ("mixed") = bit-identical legacy behavior AND wire
  bytes: heartbeats, ``/stats`` fields, fleet snapshots all match the
  pre-disagg fleet exactly.
- Two-hop serving is output-identical to single-pod serving under greedy
  decoding: the prefill pod stops at the first token, the decode pod
  pulls the chain and streams the rest — same tokens, in order.
- Overload sheds at the PREFILL tier (fast ``AdmissionError`` with a
  Retry-After hint, decode tier untouched); deadlines clamp across both
  hops.
- Chaos: a decode pod dying mid-handoff re-plans (prefill work reused);
  a draining prefill pod is never picked and the fleet degrades to
  single-pod serving — no orphaned chains, pages back to baseline.
"""

import time

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.disagg import (
    DisaggConfig,
    DisaggCoordinator,
    PlanError,
    PodView,
    TwoHopPlanner,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    EventBatch,
    FleetHealth,
    Heartbeat,
    KVEventsPool,
    KVEventsPoolConfig,
    Message,
    PrefillComplete,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    IndexConfig,
    create_index,
)
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import (
    AdmissionError,
    PodServer,
    PodServerConfig,
)

PS = 4
MODEL = "tiny-llama"


def _engine_cfg(total_pages=64, **kw):
    kw.setdefault("scheduler", SchedulerConfig(max_prefill_batch=4))
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=PS),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )


def _pod_config(pod_id, total_pages=64, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=False,
        engine=_engine_cfg(total_pages=total_pages),
        **kw,
    )


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _endpoint():
    from conftest import free_tcp_port

    return f"tcp://127.0.0.1:{free_tcp_port()}"


class _Fleet:
    """Context manager: start/stop a named set of PodServers."""

    def __init__(self, **pods):
        self.pods = pods

    def __enter__(self):
        for pod in self.pods.values():
            pod.start()
        return self.pods

    def __exit__(self, *exc):
        for pod in self.pods.values():
            pod.shutdown()


class TestTwoHopPlanner:
    """Placement unit pins: tier split, warmth/rate/headroom ordering,
    health exclusions, re-plan excludes, fallback collapse."""

    def VIEWS(self):
        return [
            PodView("pre-a", role="prefill", transfer_endpoint="tcp://a",
                    prefill_rate=100.0, queue_depth=2),
            PodView("pre-b", role="prefill", transfer_endpoint="tcp://b",
                    prefill_rate=400.0, queue_depth=2),
            PodView("dec-a", role="decode", queue_depth=3),
            PodView("dec-b", role="decode", queue_depth=1),
        ]

    def test_warmth_dominates_prefill_pick(self):
        pl = TwoHopPlanner(score_fn=lambda t, names: {"pre-a": 4})
        plan = pl.plan([1, 2], self.VIEWS())
        assert plan.mode == "disagg"
        assert plan.prefill_pod == "pre-a"  # warm beats the faster pod
        assert plan.pull_source == "tcp://a"
        assert plan.prefill_score == 4

    def test_rate_breaks_warmth_ties_and_headroom_picks_decode(self):
        pl = TwoHopPlanner()
        plan = pl.plan([1, 2], self.VIEWS())
        assert plan.prefill_pod == "pre-b"  # no warmth: measured rate wins
        assert plan.decode_pod == "dec-b"  # shallowest queue = headroom

    def test_prefill_only_pod_never_wins_decode_and_vice_versa(self):
        pl = TwoHopPlanner()
        plan = pl.plan([1], self.VIEWS())
        assert plan.decode_pod.startswith("dec-")
        assert plan.prefill_pod.startswith("pre-")

    def test_draining_dead_breaker_excluded(self):
        views = self.VIEWS()
        views[1].draining = True  # pre-b
        views[3].dead = True  # dec-b
        plan = TwoHopPlanner().plan([1], views)
        assert plan.prefill_pod == "pre-a" and plan.decode_pod == "dec-a"
        views[0].breaker_open = True  # pre-a's export plane suspect
        plan2 = TwoHopPlanner().plan([1], views)
        assert plan2.mode == "single"  # no healthy exporter left
        assert plan2.decode_pod == "dec-a"

    def test_exclude_replans_around_failed_pod(self):
        pl = TwoHopPlanner()
        plan = pl.plan([1], self.VIEWS(), exclude={"dec-b"})
        assert plan.decode_pod == "dec-a"

    def test_mixed_coincide_collapses_to_single(self):
        views = [PodView("m0", role="mixed", transfer_endpoint="tcp://m")]
        plan = TwoHopPlanner().plan([1], views)
        assert plan.mode == "single" and plan.decode_pod == "m0"

    def test_no_exporter_falls_back_single_at_warmth(self):
        views = [
            PodView("m0", role="mixed", queue_depth=0),
            PodView("m1", role="mixed", queue_depth=5),
        ]
        pl = TwoHopPlanner(score_fn=lambda t, names: {"m1": 7})
        plan = pl.plan([1], views)
        assert plan.mode == "single" and plan.decode_pod == "m1"

    def test_prefill_only_fleet_raises(self):
        views = [PodView("p", role="prefill", transfer_endpoint="tcp://p")]
        with pytest.raises(PlanError):
            TwoHopPlanner().plan([1], views)

    def test_all_dead_raises(self):
        views = [PodView("a", dead=True), PodView("b", draining=True)]
        with pytest.raises(PlanError):
            TwoHopPlanner().plan([1], views)


class TestRoleWireFormat:
    """Heartbeat role is a trailing append; PrefillComplete round-trips;
    role-less traffic is byte-identical legacy."""

    def test_roleless_heartbeat_bytes_pinned_legacy(self):
        import msgpack

        legacy = msgpack.packb(
            [0.0, [["Heartbeat", 3]]], use_bin_type=True
        )
        now = EventBatch(ts=0.0, events=[Heartbeat(dropped_batches=3)])
        assert now.to_payload() == legacy
        draining = msgpack.packb(
            [0.0, [["Heartbeat", 3, True]]], use_bin_type=True
        )
        now_d = EventBatch(
            ts=0.0, events=[Heartbeat(dropped_batches=3, draining=True)]
        )
        assert now_d.to_payload() == draining

    def test_role_heartbeat_round_trip(self):
        batch = EventBatch(
            ts=0.0,
            events=[Heartbeat(dropped_batches=1, role="prefill")],
        )
        ev = decode_event_batch(batch.to_payload()).events[0]
        assert ev.role == "prefill" and ev.draining is False
        batch2 = EventBatch(
            ts=0.0,
            events=[Heartbeat(dropped_batches=1, draining=True, role="decode")],
        )
        ev2 = decode_event_batch(batch2.to_payload()).events[0]
        assert ev2.role == "decode" and ev2.draining is True

    def test_unknown_role_decodes_to_none(self):
        import msgpack

        payload = msgpack.packb(
            [0.0, [["Heartbeat", 0, False, "gpu-turbo"]]], use_bin_type=True
        )
        ev = decode_event_batch(payload).events[0]
        assert ev.role is None  # tolerant: never breaks liveness

    def test_prefill_complete_round_trip_and_tolerance(self):
        import msgpack

        batch = EventBatch(
            ts=0.0, events=[PrefillComplete(request_id="r-1", num_blocks=9)]
        )
        ev = decode_event_batch(batch.to_payload()).events[0]
        assert isinstance(ev, PrefillComplete)
        assert ev.request_id == "r-1" and ev.num_blocks == 9
        # Truncated legacy-style frame: fields default, never a poison pill.
        short = msgpack.packb([0.0, [["PrefillComplete"]]], use_bin_type=True)
        ev2 = decode_event_batch(short).events[0]
        assert ev2.request_id == "" and ev2.num_blocks == 0


class TestRolePlacementFilter:
    """Heartbeat → pool → FleetHealth role propagation and the scorer's
    placement filter; snapshot keys stay legacy for role-less fleets."""

    def _health_with_roles(self):
        health = FleetHealth()
        index = create_index(IndexConfig())
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1), health=health)
        pool.start()
        try:
            for pod, role in (
                ("pre-0", "prefill"), ("dec-0", "decode"), ("mix-0", None),
            ):
                batch = EventBatch(
                    ts=0.0, events=[Heartbeat(dropped_batches=0, role=role)]
                )
                pool.add_task(
                    Message(
                        topic=f"kv@{pod}@{MODEL}", pod_identifier=pod,
                        model_name=MODEL, payload=batch.to_payload(), seq=1,
                    )
                )
            pool.add_task(
                Message(
                    topic=f"kv@pre-0@{MODEL}", pod_identifier="pre-0",
                    model_name=MODEL, seq=2,
                    payload=EventBatch(
                        ts=0.0, events=[PrefillComplete("r", 3)]
                    ).to_payload(),
                )
            )
            assert pool.drain(timeout=10.0)
        finally:
            pool.shutdown()
        return health

    def test_placement_filter_excludes_wrong_tier(self):
        health = self._health_with_roles()
        scores = {"pre-0": 5, "dec-0": 3, "mix-0": 1}
        assert health.filter_scores(scores) == scores  # legacy: role-blind
        assert health.filter_scores(scores, placement="decode") == {
            "dec-0": 3, "mix-0": 1,
        }
        assert health.filter_scores(scores, placement="prefill") == {
            "pre-0": 5, "mix-0": 1,
        }
        assert health.role_of("pre-0") == "prefill"
        assert health.role_of("mix-0") is None

    def test_pod_views_and_prefill_supply_counter(self):
        health = self._health_with_roles()
        views = health.pod_views()
        assert views["pre-0"]["role"] == "prefill"
        assert views["mix-0"]["role"] is None
        assert not views["dec-0"]["draining"]
        snap = health.snapshot()
        assert snap["prefills_completed"] == 1
        assert snap["pods"]["pre-0"]["role"] == "prefill"
        assert "role" not in snap["pods"]["mix-0"]

    def test_roleless_snapshot_keys_stay_legacy(self):
        health = FleetHealth()
        health.observe_heartbeat("pod-a", 0)
        snap = health.snapshot()
        assert "prefills_completed" not in snap
        assert set(snap["pods"]["pod-a"]) == {
            "suspect", "swept", "draining", "drained", "age_s",
        }

    def test_indexer_threads_placement(self):
        from llm_d_kv_cache_manager_tpu.kvcache import (
            KVCacheIndexer,
            KVCacheIndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            Key,
            PodEntry,
            TokenProcessorConfig,
        )

        health = self._health_with_roles()
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            ),
            fleet_health=health,
        )
        tokens = _prompt(3, 8)
        hashes = indexer.token_processor.prefix_hashes(tokens)
        keys = [Key(MODEL, h) for h in hashes]
        indexer.kv_block_index.add(
            keys, [PodEntry("pre-0"), PodEntry("dec-0")]
        )
        both = indexer.score_tokens(tokens, MODEL)
        assert set(both) == {"pre-0", "dec-0"}
        decode_only = indexer.score_tokens(tokens, MODEL, placement="decode")
        assert set(decode_only) == {"dec-0"}
        prefill_only = indexer.score_tokens(tokens, MODEL, placement="prefill")
        assert set(prefill_only) == {"pre-0"}
        indexer.shutdown()


class TestRoleGating:
    """POD_ROLE=prefill stops at the first token; mixed is untouched."""

    def test_prefill_role_clamps_to_first_token(self):
        pod = PodServer(_pod_config("rg-pre", pod_role="prefill"))
        pod.start()
        try:
            seq = pod.generate(
                _prompt(5, 10), SamplingParams(max_new_tokens=16), timeout=120
            )
            assert len(seq.generated_tokens) == 1  # ingest stopped at t1
            assert pod.role_clamped_requests == 1
            # The chain is registered and exportable (full prompt pages).
            assert seq.num_registered_pages == 10 // PS
        finally:
            pod.shutdown()

    def test_mixed_role_unclamped(self):
        pod = PodServer(_pod_config("rg-mix"))
        pod.start()
        try:
            seq = pod.generate(
                _prompt(5, 10), SamplingParams(max_new_tokens=5), timeout=120
            )
            assert len(seq.generated_tokens) == 5
            assert pod.role_clamped_requests == 0
        finally:
            pod.shutdown()

    def test_invalid_role_rejected(self):
        with pytest.raises(ValueError):
            PodServer(_pod_config("rg-bad", pod_role="gpu"))

    def test_stats_disagg_block_gated_on_role(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        async def fetch_stats(server):
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.get("/stats")
                return await resp.json()
            finally:
                await client.close()

        on = PodServer(_pod_config("rg-on", pod_role="prefill"))
        off = PodServer(_pod_config("rg-off"))
        on.start(), off.start()
        try:
            stats_on = asyncio.run(fetch_stats(on))
            stats_off = asyncio.run(fetch_stats(off))
            assert stats_on["disagg"] == {
                "role": "prefill",
                "role_clamped_requests": 0,
                "prefill_completes_published": 0,
            }
            assert "disagg" not in stats_off
        finally:
            on.shutdown(), off.shutdown()


def _disagg_fleet(*, async_pull=True, decode_pods=1, **co_kw):
    """1 prefill + N decode pods with the transfer plane wired, plus the
    coordinator over them."""
    ep = _endpoint()
    pods = {
        "pre": PodServer(
            _pod_config("pre", pod_role="prefill", transfer_endpoint=ep)
        ),
    }
    for i in range(decode_pods):
        pods[f"dec{i}"] = PodServer(
            _pod_config(f"dec{i}", pod_role="decode", async_pull=async_pull)
        )
    return pods, DisaggConfig(**co_kw)


class TestDisaggServing:
    def test_greedy_parity_disagg_vs_single_pod(self):
        pods, cfg = _disagg_fleet()
        ref = PodServer(_pod_config("ref"))
        with _Fleet(ref=ref, **pods):
            co = DisaggCoordinator(pods, cfg)
            for seed, n, max_new in ((1, 19, 8), (2, 7, 4), (3, 33, 6)):
                p = _prompt(seed, n)
                r = co.generate(p, SamplingParams(max_new_tokens=max_new))
                s_ref = ref.generate(
                    p, SamplingParams(max_new_tokens=max_new), timeout=120
                )
                assert r.mode == "disagg", (seed, r)
                assert r.tokens == s_ref.generated_tokens, seed
            # The handoff actually moved warmth: the decode hop cache-hit
            # the imported chain (full prompt pages), not a cold prefill.
            assert r.decode_cached_tokens >= (33 // PS) * PS
            assert co.stats()["handoffs"] == 3
            assert pods["pre"].prefill_completes_published == 0  # no publisher

    def test_blocking_pull_decode_pod_parity(self):
        # Decode pod without ASYNC_PULL: the coordinator degrades to the
        # PR 2 blocking pull — same output, same warm hit.
        pods, cfg = _disagg_fleet(async_pull=False)
        ref = PodServer(_pod_config("ref2"))
        with _Fleet(ref=ref, **pods):
            co = DisaggCoordinator(pods, cfg)
            p = _prompt(7, 21)
            r = co.generate(p, SamplingParams(max_new_tokens=5))
            s_ref = ref.generate(p, SamplingParams(max_new_tokens=5), timeout=120)
            assert r.tokens == s_ref.generated_tokens
            assert r.decode_cached_tokens >= (21 // PS) * PS

    def test_single_token_request_never_touches_decode_tier(self):
        pods, cfg = _disagg_fleet()
        with _Fleet(**pods):
            co = DisaggCoordinator(pods, cfg)
            r = co.generate(_prompt(9, 10), SamplingParams(max_new_tokens=1))
            assert r.mode == "disagg" and r.decode_pod is None
            assert len(r.tokens) == 1
            assert pods["dec0"].queue_depth == 0

    def test_admission_sheds_at_prefill_tier_with_retry_after(self):
        ep = _endpoint()
        pods = {
            "pre": PodServer(
                _pod_config(
                    "pre-shed", pod_role="prefill", transfer_endpoint=ep,
                    admission_max_queued_tokens=8,
                )
            ),
            "dec0": PodServer(
                _pod_config("dec-shed", pod_role="decode", async_pull=True)
            ),
        }
        with _Fleet(**pods):
            co = DisaggCoordinator(pods)
            with pytest.raises(AdmissionError) as ei:
                co.generate(_prompt(11, 16), SamplingParams(max_new_tokens=4))
            assert ei.value.retry_after_s >= 1.0  # the Retry-After hint
            # The shed never reached the decode tier.
            assert pods["dec0"].queue_depth == 0
            assert pods["pre"].admission_rejected == 1

    def test_deadline_spans_both_hops(self):
        pods, cfg = _disagg_fleet()
        with _Fleet(**pods):
            co = DisaggCoordinator(pods, cfg)
            t0 = time.monotonic()
            r = co.generate(
                _prompt(13, 16),
                SamplingParams(max_new_tokens=32),
                deadline_s=0.02,
            )
            # The budget expired during (or before) ingest: the request
            # finishes with the deadline verdict instead of burning decode
            # capacity, well inside the transfer/hop timeouts.
            assert r.finish_reason == "deadline"
            assert time.monotonic() - t0 < 30.0

    def test_fallback_single_pod_when_no_prefill_tier(self):
        pods = {
            "m0": PodServer(_pod_config("m0")),
            "m1": PodServer(_pod_config("m1")),
        }
        with _Fleet(**pods):
            co = DisaggCoordinator(pods)
            p = _prompt(15, 12)
            r = co.generate(p, SamplingParams(max_new_tokens=4))
            assert r.mode == "single" and len(r.tokens) == 4
            assert co.stats()["single_pod_served"] == 1


class TestDisaggChaos:
    """Failure modes must never be worse than the single-pod fleet."""

    def test_decode_pod_death_mid_handoff_replans(self):
        pods, cfg = _disagg_fleet(decode_pods=2)
        ref = PodServer(_pod_config("ref-c1"))
        with _Fleet(ref=ref, **pods):
            # dec0 (shallower name) is the planner's first pick: kill it
            # after planning would race, so kill it up front and rely on
            # the coordinator's submit-failure re-plan path by keeping its
            # view alive (views are point-in-time: the planner still picks
            # it, the submit fails, the re-plan lands on dec1).
            frozen_views = DisaggCoordinator(pods)._views_fn()
            pods["dec0"].shutdown()
            co = DisaggCoordinator(pods, cfg, views_fn=lambda: frozen_views)
            p = _prompt(17, 18)
            r = co.generate(p, SamplingParams(max_new_tokens=6))
            s_ref = ref.generate(p, SamplingParams(max_new_tokens=6), timeout=120)
            assert r.tokens == s_ref.generated_tokens  # parity preserved
            assert r.decode_pod == "dec1" and r.replans == 1
            assert co.stats()["replans"] == 1

    def test_prefill_pod_drain_degrades_to_single_pod(self):
        pods, cfg = _disagg_fleet()
        pods["mix"] = PodServer(_pod_config("mix-c2"))
        with _Fleet(**pods):
            co = DisaggCoordinator(pods, cfg)
            # Warm path first: disagg works.
            r0 = co.generate(_prompt(19, 10), SamplingParams(max_new_tokens=3))
            assert r0.mode == "disagg"
            assert pods["pre"].drain(timeout_s=5.0)  # clean drain
            # Draining/drained prefill pod is never picked again; the
            # fleet serves on (decode ∪ mixed) single-pod — no worse than
            # the legacy fleet, no orphaned in-flight chains.
            r1 = co.generate(_prompt(20, 10), SamplingParams(max_new_tokens=3))
            assert r1.mode == "single"
            assert r1.decode_pod in ("dec0", "mix")
            ref = PodServer(_pod_config("ref-c2"))
            ref.start()
            try:
                s_ref = ref.generate(
                    _prompt(20, 10), SamplingParams(max_new_tokens=3), timeout=120
                )
                assert r1.tokens == s_ref.generated_tokens
            finally:
                ref.shutdown()

    def test_pages_back_to_baseline_after_disagg_traffic(self):
        pods, cfg = _disagg_fleet()
        with _Fleet(**pods):
            co = DisaggCoordinator(pods, cfg)
            dec = pods["dec0"]
            free0 = dec.engine.block_manager.num_free
            for seed in (21, 22):
                co.generate(_prompt(seed, 14), SamplingParams(max_new_tokens=4))
            # Finished sequences release their allocations; imported chain
            # pages are evictable ref-0 prefix cache, which num_free counts
            # — so the pool must be exactly back at baseline: nothing
            # leaked to dead handoffs or stuck imports.
            bm = dec.engine.block_manager
            assert bm.num_free == free0
            assert not dec._pull_jobs  # no orphaned imports

    def test_decode_replan_onto_prefill_pod_never_pulls_itself(self):
        # Decode pod dies mid-handoff and the re-plan lands the decode hop
        # on the (mixed) prefill pod itself: the chain is already local —
        # the coordinator must drop pull_source instead of making the pod
        # fetch its own chain over its own transfer endpoint.
        ep = _endpoint()
        pods = {
            "m0": PodServer(
                _pod_config("m0-c6", transfer_endpoint=ep)  # mixed exporter
            ),
            "d0": PodServer(
                _pod_config("d0-c6", pod_role="decode", async_pull=True)
            ),
        }
        ref = PodServer(_pod_config("ref-c6"))
        with _Fleet(ref=ref, **pods):
            frozen = DisaggCoordinator(pods)._views_fn()
            pods["d0"].shutdown()  # first decode pick dies; views stale
            co = DisaggCoordinator(pods, views_fn=lambda: frozen)
            p = _prompt(27, 18)
            r = co.generate(p, SamplingParams(max_new_tokens=5))
            s_ref = ref.generate(p, SamplingParams(max_new_tokens=5), timeout=120)
            assert r.tokens == s_ref.generated_tokens
            assert r.decode_pod == "m0" and r.replans == 1
            # No self-pull: the continuation was served from the pod's own
            # already-local chain, never through the transfer plane.
            assert pods["m0"].transfer_pulls == 0
            assert not pods["m0"]._transfer_pool.clients()

    def test_dead_pod_on_single_mode_plan_replans(self):
        # A mode="single" plan (all-mixed fleet, no exporter) participates
        # in the same exclude-and-re-plan machinery as the two-hop path:
        # the picked pod being dead costs one re-plan, never the request.
        pods = {
            "m0": PodServer(_pod_config("m0-c5")),
            "m1": PodServer(_pod_config("m1-c5")),
        }
        with _Fleet(**pods):
            frozen = DisaggCoordinator(pods)._views_fn()
            # The warmth-blind single-pod pick tie-breaks to the max name:
            # kill m1 with stale views so the first plan still targets it.
            pods["m1"].shutdown()
            co = DisaggCoordinator(pods, views_fn=lambda: frozen)
            r = co.generate(_prompt(25, 12), SamplingParams(max_new_tokens=3))
            assert r.mode == "single" and r.decode_pod == "m0"
            assert len(r.tokens) == 3 and r.replans == 1

    def test_dead_prefill_pod_replans_to_mixed(self):
        pods, cfg = _disagg_fleet()
        pods["mix"] = PodServer(_pod_config("mix-c4"))
        with _Fleet(**pods):
            frozen = DisaggCoordinator(pods)._views_fn()
            pods["pre"].shutdown()  # crash, not drain: views still stale
            co = DisaggCoordinator(pods, cfg, views_fn=lambda: frozen)
            r = co.generate(_prompt(23, 12), SamplingParams(max_new_tokens=3))
            # First plan targets the dead prefill pod; the hop fails and
            # the re-plan (excluding it) serves the request.
            assert len(r.tokens) == 3
            assert r.replans == 1


class TestDisaggTracing:
    def test_two_hop_handoff_is_one_trace(self):
        from llm_d_kv_cache_manager_tpu.obs.tracing import Tracer

        ep = _endpoint()
        pods = {
            "pre": PodServer(
                _pod_config(
                    "tr-pre", pod_role="prefill", transfer_endpoint=ep,
                    obs_tracing=True,
                )
            ),
            "dec0": PodServer(
                _pod_config(
                    "tr-dec", pod_role="decode", async_pull=True,
                    obs_tracing=True,
                )
            ),
        }
        tracer = Tracer(enabled=True, service="disagg-test")
        with _Fleet(**pods):
            co = DisaggCoordinator(pods, tracer=tracer)
            r = co.generate(_prompt(25, 16), SamplingParams(max_new_tokens=4))
            assert r.mode == "disagg" and r.trace_id is not None
            # One trace id spans the coordinator AND both pods.
            co_spans = [
                sp for tr in tracer.traces() if tr["trace_id"] == r.trace_id
                for sp in tr["spans"]
            ]
            names = {sp["name"] for sp in co_spans}
            assert {"disagg.request", "disagg.handoff"} <= names
            handoff = next(
                sp for sp in co_spans if sp["name"] == "disagg.handoff"
            )
            assert handoff["attrs"]["prefill_pod"] == "pre"
            assert handoff["attrs"]["decode_pod"] == "dec0"
            for pod in pods.values():
                pod_spans = [
                    sp
                    for tr in pod.tracer.traces()
                    if tr["trace_id"] == r.trace_id
                    for sp in tr["spans"]
                ]
                assert any(sp["name"] == "pod.request" for sp in pod_spans), (
                    pod.config.pod_identifier
                )
