from . import kvblock  # noqa: F401
from . import transfer  # noqa: F401
from .indexer import KVCacheIndexer, KVCacheIndexerConfig
from .router import BlendedRouter, PrefixAffinityTracker, RoutingDecision
from .scorer import (
    KVBlockScorer,
    KVBlockScorerConfig,
    LongestPrefixScorer,
    ScoringStrategy,
    new_scorer,
)

__all__ = [
    "BlendedRouter",
    "PrefixAffinityTracker",
    "RoutingDecision",
    "kvblock",
    "transfer",
    "KVCacheIndexer",
    "KVCacheIndexerConfig",
    "KVBlockScorer",
    "KVBlockScorerConfig",
    "LongestPrefixScorer",
    "ScoringStrategy",
    "new_scorer",
]
