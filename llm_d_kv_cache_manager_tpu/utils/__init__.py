from .logging import get_logger, log_context, DEBUG, TRACE

__all__ = ["get_logger", "log_context", "DEBUG", "TRACE"]
