"""Opt-in real-HF-tokenizer tests (the reference's `testing.Short()`-gated
coverage: `pkg/tokenization/tokenizer_test.go:30-113` and the ~4.5k-token
long-prefix e2e `tests/e2e/redis_mock/e2e_test.go:187-224`).

This image has no network egress and no HF cache, so the whole module
skips cleanly unless a real tokenizer loads (populate `~/.cache/huggingface`
or run on a networked machine — same opt-in story as the reference's
short-mode gating). Everything here exercises the code paths the fake
char-tokenizers used elsewhere cannot: the char→byte offset conversion on
multi-byte UTF-8, the prefix store against real (non-1:1) offsets, and the
full read path at real token counts.
"""

import pathlib

import pytest

pytestmark = pytest.mark.network

MODEL = "bert-base-uncased"


@pytest.fixture(scope="module")
def hf_tokenizer():
    from llm_d_kv_cache_manager_tpu.tokenization.tokenizer import (
        CachedHFTokenizer,
        HFTokenizerConfig,
    )

    tok = CachedHFTokenizer(HFTokenizerConfig())
    try:
        tok.encode("probe", MODEL)
    except Exception as e:  # no network / no cache
        pytest.skip(f"real tokenizer unavailable ({type(e).__name__}): {e}")
    return tok


class TestByteOffsets:
    def test_multibyte_utf8_offsets_are_byte_indexed(self, hf_tokenizer):
        # 2-byte (é), 3-byte (€, CJK), 4-byte (emoji) characters: char
        # offsets and byte offsets diverge after the first multi-byte char.
        prompt = "café €5 中文 🚀 end"
        ids, offsets = hf_tokenizer.encode(prompt, MODEL)
        data = prompt.encode("utf-8")
        assert len(ids) == len(offsets)
        last_hi = 0
        for lo, hi in offsets:
            # Byte-indexed into the UTF-8 encoding, in order, and sliceable.
            assert 0 <= lo <= hi <= len(data)
            assert lo >= last_hi or (lo, hi) == (0, 0)  # specials are (0, 0)
            if hi > lo:
                last_hi = hi
                data[lo:hi].decode("utf-8")  # slices on codepoint edges
        # The text tokens must reassemble a subsequence of the prompt bytes.
        surface = b"".join(
            data[lo:hi] for lo, hi in offsets if hi > lo
        )
        assert b"caf" in surface and "🚀".encode() in surface

    def test_ascii_offsets_match_char_offsets(self, hf_tokenizer):
        prompt = "the quick brown fox jumps over the lazy dog"
        _, offsets = hf_tokenizer.encode(prompt, MODEL)
        data = prompt.encode("utf-8")
        words = {data[lo:hi].decode() for lo, hi in offsets if hi > lo}
        assert "quick" in words and "lazy" in words


class TestPrefixStoreWithRealOffsets:
    def test_roundtrip_multibyte_prompt(self, hf_tokenizer):
        from llm_d_kv_cache_manager_tpu.tokenization.prefixstore import (
            Config,
            LRUTokenStore,
        )

        store = LRUTokenStore(Config(block_size=16))
        prompt = ("naïve café déjà-vu über straße 中文测试 🚀 " * 8).strip()
        ids, offsets = hf_tokenizer.encode(prompt, MODEL)
        store.add_tokenization(MODEL, prompt, ids, offsets)
        contained, ratio = store.find_longest_contained_tokens(prompt, MODEL)
        assert ratio > 0.8
        # A prefix of the real ids, never an over-read past a block edge.
        assert contained == ids[: len(contained)]
        assert len(contained) >= 0.7 * len(ids)

    def test_extended_prompt_reuses_prefix(self, hf_tokenizer):
        from llm_d_kv_cache_manager_tpu.tokenization.prefixstore import (
            Config,
            LRUTokenStore,
        )

        store = LRUTokenStore(Config(block_size=16))
        base = "shared system prompt with unicode décor " * 6
        ids, offsets = hf_tokenizer.encode(base, MODEL)
        store.add_tokenization(MODEL, base, ids, offsets)
        extended = base + " and a different user suffix"
        contained, _ = store.find_longest_contained_tokens(extended, MODEL)
        assert len(contained) > 0
        assert contained == ids[: len(contained)]


class TestLongPrefixE2E:
    def test_4k5_token_prompt_scores_full_chain(self, hf_tokenizer):
        """The reference's LongPrefix e2e at ~4.5k tokens through the real
        read path: tokenize → chunk-hash → index → score."""
        from llm_d_kv_cache_manager_tpu.kvcache import (
            KVCacheIndexer,
            KVCacheIndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
            TokenProcessorConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import PodEntry

        lorem = (
            pathlib.Path(__file__).parent / "golden" / "bert_prompt.txt"
        ).read_text(encoding="utf-8")
        prompt = (lorem + "\n") * 5  # ~4.5k bert tokens
        ids, _ = hf_tokenizer.encode(prompt, MODEL)
        assert len(ids) > 4000

        ix = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=16)
            )
        )
        keys = ix.token_processor.tokens_to_kv_block_keys(ids, MODEL)
        assert len(keys) == len(ids) // 16
        ix.kv_block_index.add(keys, [PodEntry("pod-a", "tpu_hbm")])
        scores = ix.score_tokens(ids, MODEL, ["pod-a", "pod-b"])
        assert scores.get("pod-a") == len(keys)
        assert "pod-b" not in scores or scores["pod-b"] == 0
        ix.shutdown()
