"""Pod scoring strategies.

Parity with reference ``pkg/kvcache/kvblock_scorer.go``: score = length of
the longest *consecutive* block-hit streak starting from block 0. The active
pod set seeds from key[0]'s pods and intersects per subsequent key; survivors
increment (``kvblock_scorer.go:77-111``).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import Sequence

from .kvblock import Key


class ScoringStrategy(str, Enum):
    LONGEST_PREFIX = "LongestPrefixMatch"


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: ScoringStrategy = ScoringStrategy.LONGEST_PREFIX


class KVBlockScorer(ABC):
    @property
    @abstractmethod
    def strategy(self) -> ScoringStrategy: ...

    @abstractmethod
    def score(
        self, keys: Sequence[Key], key_to_pods: dict[Key, list[str]]
    ) -> dict[str, int]:
        """Return pod → score for the given ordered key chain and hit map."""


class LongestPrefixScorer(KVBlockScorer):
    @property
    def strategy(self) -> ScoringStrategy:
        return ScoringStrategy.LONGEST_PREFIX

    def score(
        self, keys: Sequence[Key], key_to_pods: dict[Key, list[str]]
    ) -> dict[str, int]:
        pod_scores: dict[str, int] = {}
        if not keys:
            return pod_scores

        first_pods = key_to_pods.get(keys[0], [])
        active = set(first_pods)
        for pod in first_pods:
            pod_scores[pod] = 1

        for key in keys[1:]:
            if not active:
                break
            active &= set(key_to_pods.get(key, []))
            for pod in active:
                pod_scores[pod] += 1

        return pod_scores


def new_scorer(config: KVBlockScorerConfig | None = None) -> KVBlockScorer:
    cfg = config or KVBlockScorerConfig()
    if cfg.scoring_strategy == ScoringStrategy.LONGEST_PREFIX:
        return LongestPrefixScorer()
    raise ValueError(f"unsupported scoring strategy: {cfg.scoring_strategy}")
