"""``FleetAdapter`` over real in-process ``PodServer``s.

The deployment surface the chaos tests and the bench co-sim drive — and
the single-host answer for real: one process owns N pods (one per
accelerator slice), and the controller resizes that set. Everything the
controller needs already exists on ``PodServer``: signals come from the
pod's own SLO recorder and reuse-distance estimator, migration is
``migrate_out`` over the transfer fabric, revival is ``revive_chain``,
and retirement is the PR 7 graceful drain (which also publishes the
``PodDrained`` goodbye, so the scorer-side ``FleetHealth`` unroutes the
pod and the TTL sweeper reclaims its index entries — pod add/remove
needs no new fleet-health surface, the event plane already carries it).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ...obs.lifecycle import debug_mrc_payload
from ...utils import get_logger
from .fleet import PodSignals

log = get_logger("kvcache.controller.inprocess")


class InProcessFleet:
    """Wire a ``FleetController`` to live ``PodServer`` objects.

    ``make_pod(pod_id) -> (started PodServer, transfer_endpoint | None)``
    is the provisioning hook — the environment decides config, ports, and
    event-plane wiring; this adapter only tracks membership. Retired pods
    are drained (live migration already moved what it could; stragglers
    finish under the drain), shut down, and kept in ``retired`` so
    harnesses can assert on their final state.
    """

    def __init__(
        self,
        make_pod: Optional[Callable[[str], tuple]] = None,
        drain_timeout_s: Optional[float] = None,
        fleet_health=None,
    ):
        """``fleet_health`` (a ``kvevents.FleetHealth``, optional): told
        about membership changes immediately — ``observe_pod_added`` on
        scale-up (routable before the first heartbeat),
        ``observe_pod_removed`` on scale-down (unrouted before the drain
        starts)."""
        self._make_pod = make_pod
        self._drain_timeout_s = drain_timeout_s
        self._fleet_health = fleet_health
        self._mu = threading.Lock()
        #: pod_id -> (PodServer, transfer_endpoint | None)
        self._pods: dict[str, tuple] = {}  # guarded_by: _mu
        self._spawned = 0  # guarded_by: _mu
        self.retired: list = []  # guarded_by: _mu

    # -- membership ----------------------------------------------------------
    def register(self, pod_id: str, server, endpoint: Optional[str]) -> None:
        """Add an already-running pod to the controller's view."""
        with self._mu:
            self._pods[pod_id] = (server, endpoint)

    def server(self, pod_id: str):
        with self._mu:
            entry = self._pods.get(pod_id)
        return entry[0] if entry else None

    def pod_ids(self) -> list[str]:
        with self._mu:
            return list(self._pods)

    # -- FleetAdapter --------------------------------------------------------
    def observe(self) -> list[PodSignals]:
        with self._mu:
            pods = list(self._pods.items())
        out = []
        for pod_id, (server, endpoint) in pods:
            out.append(
                PodSignals(
                    pod_id=pod_id,
                    transfer_endpoint=endpoint,
                    capacity_blocks=(
                        server.config.engine.block_manager.total_pages - 1
                    ),
                    burn_rates=(
                        server.slo.burn_rates()
                        if server.slo is not None
                        else None
                    ),
                    mrc=(
                        debug_mrc_payload(server.mrc)[1]
                        if server.mrc is not None
                        else None
                    ),
                    live_requests=server.live_requests(),
                    draining=server.is_draining,
                )
            )
        return out

    def add_pod(self) -> Optional[PodSignals]:
        if self._make_pod is None:
            return None
        with self._mu:
            self._spawned += 1
            pod_id = f"fleet-{self._spawned}"
        try:
            server, endpoint = self._make_pod(pod_id)
        except Exception:
            log.exception("pod provisioning failed", pod=pod_id)
            return None
        self.register(pod_id, server, endpoint)
        if self._fleet_health is not None:
            self._fleet_health.observe_pod_added(pod_id)
        return PodSignals(
            pod_id=pod_id,
            transfer_endpoint=endpoint,
            capacity_blocks=server.config.engine.block_manager.total_pages - 1,
        )

    def migrate(
        self, pod_id: str, request_id: str, target_endpoint: str
    ) -> bool:
        server = self.server(pod_id)
        if server is None:
            return False
        return server.migrate_out(request_id, target_endpoint)

    def retire(self, pod_id: str) -> None:
        with self._mu:
            entry = self._pods.pop(pod_id, None)
        if entry is None:
            return
        server, _ = entry
        if self._fleet_health is not None:
            self._fleet_health.observe_pod_removed(pod_id)
        try:
            server.drain(timeout_s=self._drain_timeout_s)
        finally:
            server.shutdown()
        with self._mu:
            self.retired.append(server)

    def warm_sets(self, limit: int) -> list[tuple[str, list[int]]]:
        with self._mu:
            pods = list(self._pods.values())
        rows: list[tuple[str, list[int]]] = []
        for server, endpoint in pods:
            if not endpoint:
                continue  # nothing can be pulled from this pod
            for chain in server.warm_chains(limit):
                rows.append((endpoint, chain))
        # Hottest first = longest resident chains: the revival budget goes
        # to the prefixes whose recompute would cost the most.
        rows.sort(key=lambda r: len(r[1]), reverse=True)
        return rows[:limit]

    def revive(
        self, pod_id: str, source_endpoint: str, chain_hashes: list[int]
    ) -> int:
        server = self.server(pod_id)
        if server is None:
            return 0
        return server.revive_chain(chain_hashes, source_endpoint)
