"""wire-append-only: msgpack frames may only grow optional trailing fields.

Decoders across the fleet are positional and tolerant: old readers index
into the frame array and ignore trailing extras. That contract survives
exactly one kind of evolution — appending optional fields at the end.
This checker extracts the positional field order each wire builder emits
(the list literal plus any conditional ``append``/``extend`` tails, which
ARE the optional-trailing-field idiom) and compares it against the
committed manifest ``tools/kvlint/wire_manifest.json``:

- a committed field moved, changed, or disappeared  → flagged (reorder /
  insertion / removal breaks every deployed decoder)
- a new trailing field not yet in the manifest      → flagged until the
  manifest is updated, so the append is a reviewed, diff-visible act
- a builder the manifest doesn't know               → flagged

Covered modules: ``kvcache/transfer/protocol.py`` and
``kvcache/kvevents/events.py`` (the payload builders).
"""

from __future__ import annotations

import ast
import json
from typing import Optional

from tools.kvlint.core import Finding, ModuleUnit, RepoContext

RULE = "wire-append-only"

MANIFEST_REL = "tools/kvlint/wire_manifest.json"

#: modules whose frames are pinned (matched by repo-relative path suffix)
WIRE_MODULES = (
    "kvcache/transfer/protocol.py",
    "kvcache/kvevents/events.py",
)

#: wire-builder function name shapes
_BUILDER_NAMES = ("to_tagged_union", "to_payload")
_BUILDER_PREFIX = "encode_"


def _is_wire_module(unit: ModuleUnit) -> bool:
    return any(unit.rel.endswith(m) for m in WIRE_MODULES)


def _module_key(unit: ModuleUnit) -> str:
    for m in WIRE_MODULES:
        if unit.rel.endswith(m):
            return m
    return unit.rel


def _load_manifest(ctx: RepoContext) -> Optional[dict]:
    text = ctx.read_repo_file(MANIFEST_REL)
    if text is None:
        return None
    try:
        return json.loads(text)
    except ValueError:
        return None


def _packb_list(call: ast.Call) -> Optional[ast.List]:
    """``msgpack.packb([...], ...)`` → the frame list literal."""
    fn = call.func
    if (
        isinstance(fn, ast.Attribute)
        and fn.attr == "packb"
        and call.args
        and isinstance(call.args[0], ast.List)
    ):
        return call.args[0]
    return None


def _extract_frames(fn: ast.FunctionDef) -> dict[str, tuple[int, list[str]]]:
    """frame-name -> (lineno, ordered field expressions).

    Frames are list variables later ``append``/``extend``-ed (conditionals
    included — a conditional tail is the optional-field idiom and stays
    positional), plus any list literal passed straight to ``msgpack.packb``
    (keyed ``return``).
    """
    frames: dict[str, tuple[int, list[str]]] = {}

    def fields_of(lst: ast.List) -> list[str]:
        return [ast.unparse(e) for e in lst.elts]

    def visit(stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            # frame start: <name> = [ ... ]  (plain or annotated)
            target: Optional[str] = None
            value: Optional[ast.expr] = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
                isinstance(stmt.targets[0], ast.Name)
            ):
                target, value = stmt.targets[0].id, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                target, value = stmt.target.id, stmt.value
            if (
                target is not None
                and isinstance(value, ast.List)
                and target not in frames
            ):
                frames[target] = (stmt.lineno, fields_of(value))

            # frame growth: <name>.append(x) / <name>.extend([...])
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                f = call.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in frames
                    and call.args
                ):
                    line, flds = frames[f.value.id]
                    if f.attr == "append":
                        frames[f.value.id] = (
                            line,
                            flds + [ast.unparse(call.args[0])],
                        )
                    elif f.attr == "extend" and isinstance(call.args[0], ast.List):
                        frames[f.value.id] = (
                            line,
                            flds + fields_of(call.args[0]),
                        )

            # direct frames: ``return [ ... ]`` and ``msgpack.packb([...])``
            if (
                isinstance(stmt, ast.Return)
                and isinstance(stmt.value, ast.List)
                and "return" not in frames
            ):
                frames["return"] = (stmt.lineno, fields_of(stmt.value))
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    lst = _packb_list(sub)
                    if lst is not None and "return" not in frames:
                        frames["return"] = (sub.lineno, fields_of(lst))

            # recurse into nested blocks in order
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, attr, None)
                if inner:
                    visit(inner)
            for handler in getattr(stmt, "handlers", ()):
                visit(handler.body)
    visit(fn.body)
    return frames


def _wire_builders(unit: ModuleUnit) -> dict[str, ast.FunctionDef]:
    """qualname -> builder FunctionDef."""
    out: dict[str, ast.FunctionDef] = {}
    for node in unit.tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name in _BUILDER_NAMES:
                    out[f"{node.name}.{sub.name}"] = sub
        elif isinstance(node, ast.FunctionDef) and (
            node.name in _BUILDER_NAMES or node.name.startswith(_BUILDER_PREFIX)
        ):
            out[node.name] = node
    return out


def check(unit: ModuleUnit, ctx: RepoContext) -> list[Finding]:
    if not _is_wire_module(unit):
        return []
    manifest = _load_manifest(ctx)
    if manifest is None:
        return [
            Finding(
                rule=RULE,
                path=unit.rel,
                line=1,
                message=f"missing or unreadable wire manifest {MANIFEST_REL}",
            )
        ]
    mod_key = _module_key(unit)
    pinned: dict = manifest.get(mod_key, {})
    findings: list[Finding] = []

    builders = _wire_builders(unit)
    for qualname, fn in builders.items():
        frames = _extract_frames(fn)
        pinned_frames: dict = pinned.get(qualname, {})
        for frame, (line, got) in frames.items():
            want = pinned_frames.get(frame)
            if want is None:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.rel,
                        line=line,
                        message=(
                            f"wire frame {qualname}[{frame}] not in "
                            f"{MANIFEST_REL} — declare its field order there"
                        ),
                    )
                )
                continue
            if got[: len(want)] != want:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.rel,
                        line=line,
                        message=(
                            f"wire frame {qualname}[{frame}] reorders/mutates "
                            f"committed fields: manifest pins {want}, code "
                            f"emits {got} — deployed positional decoders "
                            "break; only optional TRAILING fields may be added"
                        ),
                    )
                )
            elif len(got) > len(want):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.rel,
                        line=line,
                        message=(
                            f"wire frame {qualname}[{frame}] grew trailing "
                            f"field(s) {got[len(want):]} — append them to "
                            f"{MANIFEST_REL} (reviewed, append-only) and "
                            "ensure decoders tolerate their absence"
                        ),
                    )
                )
        # committed frames the code no longer emits
        for frame, want in pinned_frames.items():
            if frame not in frames:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.rel,
                        line=fn.lineno,
                        message=(
                            f"wire frame {qualname}[{frame}] is pinned in the "
                            "manifest but no longer built — removing a frame "
                            "breaks deployed peers"
                        ),
                    )
                )
    # committed builders that vanished from the module
    for qualname in pinned:
        if qualname not in builders:
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.rel,
                    line=1,
                    message=(
                        f"wire builder {qualname} is pinned in the manifest "
                        "but absent from the module"
                    ),
                )
            )
    return findings
