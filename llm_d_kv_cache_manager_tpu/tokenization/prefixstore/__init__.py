"""Text-prefix → token cache, skipping re-tokenization of shared prefixes.

Parity with reference ``pkg/tokenization/prefixstore``.
"""

from .indexer import Indexer, Config
from .lru_store import LRUTokenStore
from .trie_store import ContainedTokenStore

__all__ = ["Indexer", "Config", "LRUTokenStore", "ContainedTokenStore"]
