"""Repo tooling (not shipped with the package). See ``tools/kvlint``."""
