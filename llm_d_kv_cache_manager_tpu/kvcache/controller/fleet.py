"""MRC-driven cache-aware fleet autoscaling with live KV migration.

The reconcile loop reads two fleet signals the observability planes
already export — SLO burn rates (``kvcache_slo_burn_rate``, the PR 13
``OBS_SLO`` recorder) and the fleet-aggregated miss-ratio curve (the
PR 15 ``OBS_LIFECYCLE`` reuse-distance estimator, merged by
``aggregate_mrc``) — and decides pod count:

- **scale up** when the burn rate crosses ``burn_threshold`` AND the MRC
  predicts real hit-rate headroom at one more pod's capacity: latency is
  burning *and* more cache would actually absorb it. A burning fleet
  whose curve is flat is compute-bound, not cache-bound — the controller
  records the blocked decision (the operator's cue to scale compute or
  shed load) instead of buying pages that cannot help. The new pod is
  revived warm: the survivors' ``IndexSnapshot`` digests name their hot
  chains, and targeted pulls over the transfer fabric seed the newcomer
  before the router starts counting on its hit rate.
- **scale down** when the burn rate is comfortably idle (a quarter of
  the threshold) and the curve is flat at current capacity — the last
  pod's pages are not earning their keep. The victim's in-flight decode
  sequences are LIVE-MIGRATED to survivors (``PodServer.migrate_out``:
  full KV chain + decode state over the transfer fabric, resumed
  mid-sequence with greedy-parity output), so scale-down completes in
  transfer time instead of a drain's worth of decode tail; any failed
  migration falls back to finishing locally under the normal drain.

Both directions share one hysteresis clock: after ANY scaling action the
controller holds for ``hysteresis_s`` — a burst that triggers scale-up
the moment a scale-down finishes cannot flap the fleet.

Everything is off by default: ``FLEET_CONTROLLER`` unset builds no
controller, starts no thread, and every pod behaves — and speaks on
every wire — bit-identically to the legacy fleet. The controller talks
to its fleet through the small ``FleetAdapter`` surface below, so the
decision logic is identical whether the pods are in-process
(``InProcessFleet``: tests, bench, single-host) or a deployment
environment's replica set.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from ...utils import get_logger
from .mrc import aggregate_mrc, hit_rate_at

log = get_logger("kvcache.controller.fleet")


@dataclass
class PodSignals:
    """One pod's controller-relevant state, as the adapter observed it."""

    pod_id: str
    #: the pod's transfer endpoint (migration/revival target), None when
    #: the pod exports nothing — it can still be scaled away, but nothing
    #: can be migrated or revived *to* it
    transfer_endpoint: Optional[str] = None
    #: usable HBM page capacity (total_pages - 1, the allocator's view)
    capacity_blocks: int = 0
    #: ``SLORecorder.burn_rates()`` shape, None when OBS_SLO is off
    burn_rates: Optional[dict] = None
    #: ``/debug/mrc`` payload shape, None when OBS_LIFECYCLE is off
    mrc: Optional[dict] = None
    #: request ids of live (admitted, unfinished) sequences
    live_requests: list[str] = field(default_factory=list)
    #: pod is already draining — never a migration target, never a victim
    draining: bool = False


class FleetAdapter(Protocol):
    """What the controller needs from its deployment environment."""

    def observe(self) -> list[PodSignals]:
        """Current signals for every active pod."""

    def add_pod(self) -> Optional[PodSignals]:
        """Provision one pod; None when the environment cannot."""

    def migrate(
        self, pod_id: str, request_id: str, target_endpoint: str
    ) -> bool:
        """Live-migrate one request off ``pod_id``; True when the target
        resumed it (False = it resumes locally and drains out)."""

    def retire(self, pod_id: str) -> None:
        """Drain and decommission ``pod_id`` (stragglers the migrations
        missed finish under the pod's own drain)."""

    def warm_sets(self, limit: int) -> list[tuple[str, list[int]]]:
        """Hot chains to revive on a new pod: ``(donor transfer endpoint,
        chain block hashes)`` rows, hottest first."""

    def revive(
        self, pod_id: str, source_endpoint: str, chain_hashes: list[int]
    ) -> int:
        """Pull one chain onto ``pod_id`` from a donor; blocks imported."""


@dataclass
class FleetControllerConfig:
    #: master switch (``FLEET_CONTROLLER``); off = nothing constructed
    enabled: bool = False
    #: reconcile cadence (``FLEET_RECONCILE_INTERVAL_S``)
    reconcile_interval_s: float = 5.0
    #: fleet-max burn rate (any objective, any window) at or over which
    #: the fleet is burning (``FLEET_BURN_THRESHOLD``); scale-down
    #: requires calm — burn under a quarter of this
    burn_threshold: float = 2.0
    #: minimum predicted hit-rate gain (scale-up) or loss (scale-down)
    #: one pod's capacity must make on the fleet MRC
    #: (``FLEET_MRC_HEADROOM``)
    mrc_headroom: float = 0.02
    #: hold-down after ANY scaling action (``FLEET_HYSTERESIS_S``)
    hysteresis_s: float = 60.0
    #: pod-count floor/ceiling (``FLEET_MIN_PODS``/``FLEET_MAX_PODS``)
    min_pods: int = 1
    max_pods: int = 8
    #: warm-revival budget per scale-up: at most this many chains pulled
    revive_chains: int = 8

    @classmethod
    def from_env(cls) -> "FleetControllerConfig":
        cfg = cls()
        cfg.enabled = os.environ.get("FLEET_CONTROLLER", "0").lower() in (
            "1",
            "true",
            "yes",
        )
        cfg.reconcile_interval_s = float(
            os.environ.get("FLEET_RECONCILE_INTERVAL_S", cfg.reconcile_interval_s)
        )
        cfg.burn_threshold = float(
            os.environ.get("FLEET_BURN_THRESHOLD", cfg.burn_threshold)
        )
        cfg.mrc_headroom = float(
            os.environ.get("FLEET_MRC_HEADROOM", cfg.mrc_headroom)
        )
        cfg.hysteresis_s = float(
            os.environ.get("FLEET_HYSTERESIS_S", cfg.hysteresis_s)
        )
        cfg.min_pods = int(os.environ.get("FLEET_MIN_PODS", cfg.min_pods))
        cfg.max_pods = int(os.environ.get("FLEET_MAX_PODS", cfg.max_pods))
        return cfg


@dataclass
class FleetDecision:
    """One reconcile pass's verdict — also the flight-recorder row."""

    action: str  # "scale_up" | "scale_down" | "hold"
    reason: str
    pods: int
    burn: Optional[float] = None
    #: predicted fleet hit rate at current capacity / one pod more / less
    hit_now: Optional[float] = None
    hit_up: Optional[float] = None
    hit_down: Optional[float] = None
    #: scale-down victim / scale-up newcomer
    pod_id: Optional[str] = None
    migrated: int = 0
    migration_fallbacks: int = 0
    revived_blocks: int = 0

    def as_attrs(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


def fleet_burn(pods: list[PodSignals]) -> Optional[float]:
    """The fleet's burn rate: max over pods, objectives, and windows —
    one pod burning IS the fleet burning (the router sent it that
    traffic). None when no pod reports any measured window."""
    worst: Optional[float] = None
    for pod in pods:
        for windows in (pod.burn_rates or {}).values():
            for rate in windows.values():
                if rate is not None and (worst is None or rate > worst):
                    worst = rate
    return worst


class FleetController:
    """The reconcile loop: observe → decide → act, with hysteresis.

    ``reconcile()`` is one synchronous pass (what the tests and the bench
    co-sim drive directly); ``start()`` runs it on a daemon thread every
    ``reconcile_interval_s``. ``flight`` (an ``obs.flight.FlightRecorder``,
    optional) receives one ``scale_up``/``scale_down`` event per scaling
    action — the postmortem trail for "why did the fleet resize".
    """

    def __init__(
        self,
        config: FleetControllerConfig,
        adapter: FleetAdapter,
        flight=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config
        self.adapter = adapter
        self.flight = flight
        self._clock = clock
        self._mu = threading.Lock()
        self._last_action_t: Optional[float] = None  # guarded_by: _mu
        self.decisions: deque = deque(maxlen=256)  # guarded_by: _mu
        self.reconciles = 0  # guarded_by: _mu
        self.scale_ups = 0  # guarded_by: _mu
        self.scale_downs = 0  # guarded_by: _mu
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the decision --------------------------------------------------------
    def _decide(self, pods: list[PodSignals]) -> FleetDecision:
        """Pure decision over one observation (no side effects): what the
        flap test pins. Capacities are evaluated per pod-quantum — the
        mean pod's usable pages — because that is the unit a scaling
        action actually adds or removes."""
        cfg = self.config
        n = len(pods)
        burn = fleet_burn(pods)
        agg = aggregate_mrc({p.pod_id: p.mrc for p in pods})
        cap_now = sum(p.capacity_blocks for p in pods)
        quantum = cap_now // n if n else 0
        hit_now = hit_rate_at(agg["curve"], cap_now) if cap_now else None
        hit_up = (
            hit_rate_at(agg["curve"], cap_now + quantum) if quantum else None
        )
        hit_down = (
            hit_rate_at(agg["curve"], cap_now - quantum)
            if quantum and n > 1
            else None
        )
        base = dict(
            pods=n, burn=burn, hit_now=hit_now, hit_up=hit_up,
            hit_down=hit_down,
        )

        with self._mu:
            held = (
                self._last_action_t is not None
                and self._clock() - self._last_action_t < cfg.hysteresis_s
            )
        if held:
            return FleetDecision("hold", "hysteresis", **base)

        burning = burn is not None and burn >= cfg.burn_threshold
        if burning:
            if n >= cfg.max_pods:
                return FleetDecision("hold", "burning_at_max_pods", **base)
            if hit_now is None or hit_up is None:
                return FleetDecision("hold", "burning_no_mrc", **base)
            if hit_up - hit_now < cfg.mrc_headroom:
                # Latency burns but the curve is flat: more cache cannot
                # absorb it — compute-bound, the operator's call.
                return FleetDecision("hold", "burning_mrc_flat", **base)
            return FleetDecision("scale_up", "burn_with_mrc_headroom", **base)

        calm = burn is None or burn <= cfg.burn_threshold / 4.0
        if (
            calm
            and n > cfg.min_pods
            and hit_now is not None
            and hit_down is not None
            and hit_now - hit_down < cfg.mrc_headroom
        ):
            return FleetDecision("scale_down", "idle_mrc_flat", **base)
        return FleetDecision("hold", "steady", **base)

    # -- the actions ---------------------------------------------------------
    def _pick_victim(self, pods: list[PodSignals]) -> Optional[PodSignals]:
        """Cheapest pod to remove: fewest live sequences to migrate (ties
        to the smallest capacity — evicting the least cache)."""
        candidates = [p for p in pods if not p.draining]
        if not candidates:
            return None
        return min(
            candidates,
            key=lambda p: (len(p.live_requests), p.capacity_blocks),
        )

    def _scale_down(
        self, pods: list[PodSignals], decision: FleetDecision
    ) -> FleetDecision:
        victim = self._pick_victim(pods)
        if victim is None:
            decision.action, decision.reason = "hold", "no_victim"
            return decision
        decision.pod_id = victim.pod_id
        survivors = [
            p
            for p in pods
            if p.pod_id != victim.pod_id
            and not p.draining
            and p.transfer_endpoint
        ]
        # Spread the victim's sequences across survivors, least-loaded
        # first; a survivor that refuses (draining, admission caps) just
        # means that sequence finishes locally under the drain.
        load = {p.pod_id: len(p.live_requests) for p in survivors}
        for rid in victim.live_requests:
            if not survivors:
                decision.migration_fallbacks += 1
                continue
            target = min(survivors, key=lambda p: load[p.pod_id])
            ok = False
            try:
                ok = self.adapter.migrate(
                    victim.pod_id, rid, target.transfer_endpoint
                )
            except Exception:
                log.exception(
                    "migration failed", request=rid, victim=victim.pod_id
                )
            if ok:
                decision.migrated += 1
                load[target.pod_id] += 1
            else:
                decision.migration_fallbacks += 1
        try:
            self.adapter.retire(victim.pod_id)
        except Exception:
            log.exception("retire failed", victim=victim.pod_id)
            decision.action, decision.reason = "hold", "retire_failed"
            return decision
        with self._mu:
            self.scale_downs += 1
        return decision

    def _scale_up(
        self, pods: list[PodSignals], decision: FleetDecision
    ) -> FleetDecision:
        try:
            newcomer = self.adapter.add_pod()
        except Exception:
            log.exception("add_pod failed")
            newcomer = None
        if newcomer is None:
            decision.action, decision.reason = "hold", "add_pod_failed"
            return decision
        decision.pod_id = newcomer.pod_id
        # Warm revival: seed the newcomer with the fleet's hot chains so
        # the router's next MRC read shows the capacity actually earning
        # hits instead of a cold pod dragging the aggregate down.
        try:
            sets = self.adapter.warm_sets(self.config.revive_chains)
        except Exception:
            log.exception("warm_sets failed; new pod starts cold")
            sets = []
        for source_endpoint, hashes in sets[: self.config.revive_chains]:
            if not hashes:
                continue
            try:
                decision.revived_blocks += self.adapter.revive(
                    newcomer.pod_id, source_endpoint, list(hashes)
                )
            except Exception:
                log.exception(
                    "warm revival pull failed", source=source_endpoint
                )
        with self._mu:
            self.scale_ups += 1
        return decision

    # -- the loop ------------------------------------------------------------
    def reconcile(self) -> FleetDecision:
        """One observe → decide → act pass."""
        pods = [p for p in self.adapter.observe() if not p.draining]
        decision = self._decide(pods)
        if decision.action == "scale_down":
            decision = self._scale_down(pods, decision)
        elif decision.action == "scale_up":
            decision = self._scale_up(pods, decision)
        now = self._clock()
        with self._mu:
            self.reconciles += 1
            if decision.action in ("scale_up", "scale_down"):
                self._last_action_t = now
            self.decisions.append(decision)
        if decision.action in ("scale_up", "scale_down"):
            log.info("fleet scaling action", **decision.as_attrs())
            if self.flight is not None:
                self.flight.record_event(
                    decision.action, **decision.as_attrs()
                )
                self.flight.trigger(decision.action)
        return decision

    def start(self) -> None:
        if not self.config.enabled:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-controller", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.config.reconcile_interval_s):
            try:
                self.reconcile()
            except Exception:
                # The loop must survive any adapter fault: a controller
                # that dies silently leaves the fleet stuck at whatever
                # size the fault found it.
                log.exception("reconcile pass failed")

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "reconciles": self.reconciles,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "last_decision": (
                    self.decisions[-1].as_attrs() if self.decisions else None
                ),
            }
