"""Long-prompt interference microbenchmark: decode ITL during prompt ingest.

The stall this measures: with legacy either-or scheduling, one long prompt's
prefill occupies a whole engine step, so every running decode lane's
inter-token latency (ITL) spikes by the full prefill wall time — exactly
when the fleet is busiest. Chunked prefill (`chunked_prefill_tokens`)
splits the ingest into budget-sized chunks and carries the decode lanes in
the same (mixed) step, bounding the spike at one chunk's compute.

Method: start a batch of decode lanes, reach steady state, inject one
long prompt, and record every lane's inter-token wall times from injection
until the long prompt finishes. Reported per arm (unchunked vs chunked):

- ``p90_itl_ms`` — p90 of decode ITL samples in the interference window
  (the stall tail the ROADMAP north-star cares about);
- ``ttft_s`` — the long prompt's time to first token (the trade-off side:
  chunking defers the long prompt's completion);
- ``total_tok_s`` — all tokens committed in the window / window wall time
  (chunking must not buy ITL with meaningful total-throughput loss).

One JSON line per arm plus a ``comparison`` line with the headline ratios.

Env knobs: BENCH_MODEL (smoke|1p4b), BENCH_LONG_LEN, BENCH_CHUNK_BUDGET,
BENCH_LANES, BENCH_DECODE_STEPS (fused burst size; 1 = cleanest ITL).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_arm(
    chunked, model_cfg, *, long_len, lanes, page, total_pages, budget,
    decode_steps, interpret, params,
):
    from llm_d_kv_cache_manager_tpu.server import (
        BlockManagerConfig,
        Engine,
        EngineConfig,
        SamplingParams,
        SchedulerConfig,
    )

    max_len = long_len + 256
    cfg = EngineConfig(
        model=model_cfg,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=page),
        scheduler=SchedulerConfig(
            max_prefill_batch=4,
            max_prefill_tokens=8192,
            chunked_prefill_tokens=budget if chunked else None,
        ),
        max_model_len=max_len,
        decode_batch_size=lanes + 1,
        decode_steps_per_iter=decode_steps,
        prefill_bucket=64,
        prefill_ctx_bucket=-(-max_len // page),
        decode_pages_bucket=-(-max_len // page),
        interpret=interpret,
    )
    rng = np.random.default_rng(7)
    eng = Engine(cfg, params=params)

    vocab = model_cfg.vocab_size
    lane_seqs = [
        eng.add_request(
            rng.integers(0, vocab, 48).tolist(),
            SamplingParams(max_new_tokens=10_000),
        )
        for _ in range(lanes)
    ]
    # Steady state: every lane decoding, shapes warm. The warm long prompt
    # has the SAME length as the measured one so every executable the
    # interference window hits (whole-prompt prefill, every chunk/ctx
    # width, mixed-step decode) is compiled before timing starts.
    while any(s.num_generated == 0 for s in lane_seqs):
        eng.step()
    warm = eng.add_request(
        rng.integers(0, vocab, long_len).tolist(),
        SamplingParams(max_new_tokens=1),
    )
    while not warm.is_finished():
        eng.step()
    for _ in range(4):
        eng.step()

    # Interference window: inject the long prompt, sample lane ITLs until
    # it finishes generating.
    long_seq = eng.add_request(
        rng.integers(0, vocab, long_len).tolist(),
        SamplingParams(max_new_tokens=8),
    )
    t0 = time.perf_counter()
    last_commit = {s.seq_id: t0 for s in lane_seqs}
    gen_at = {s.seq_id: s.num_generated for s in lane_seqs}
    itl = []
    tok0 = sum(s.num_generated for s in lane_seqs)
    while not long_seq.is_finished() and eng.has_work:
        eng.step()
        now = time.perf_counter()
        for s in lane_seqs:
            d = s.num_generated - gen_at[s.seq_id]
            if d > 0:
                # Fused bursts commit d tokens at once; attribute the
                # inter-commit wall evenly.
                dt = (now - last_commit[s.seq_id]) / d
                itl.extend([dt] * d)
                last_commit[s.seq_id] = now
                gen_at[s.seq_id] = s.num_generated
    wall = time.perf_counter() - t0
    total_tok = (
        sum(s.num_generated for s in lane_seqs) - tok0 + long_seq.num_generated
    )
    return {
        "p90_itl_ms": float(np.percentile(itl, 90) * 1e3) if itl else None,
        "mean_itl_ms": float(np.mean(itl) * 1e3) if itl else None,
        "itl_samples": len(itl),
        "ttft_s": round(long_seq.ttft, 4) if long_seq.ttft else None,
        "total_tok_s": round(total_tok / wall, 2),
        "window_s": round(wall, 3),
    }


def main() -> int:
    import jax

    from llm_d_kv_cache_manager_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    mode = os.environ.get("BENCH_MODEL", "1p4b" if on_tpu else "smoke")
    if mode == "1p4b":
        import jax.numpy as jnp

        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        model_cfg = LlamaConfig(
            vocab_size=32_000,
            hidden_size=3072,
            intermediate_size=8192,
            n_layers=12,
            n_heads=24,
            n_kv_heads=8,
            rope_scaling=llama.LLAMA_3_8B.rope_scaling,
            dtype=jnp.bfloat16,
        )
        long_len, lanes, page, total_pages = 2048, 6, 16, 2048
        budget, decode_steps, interpret = 256, 1, False
    else:
        model_cfg = llama.TINY_LLAMA
        # 2k ingest even in smoke: the stall under test IS the long
        # prompt; results/chunked_prefill.md records this config.
        long_len, lanes, page, total_pages = 2048, 3, 16, 256
        budget, decode_steps, interpret = 128, 1, True

    long_len = int(os.environ.get("BENCH_LONG_LEN", long_len))
    budget = int(os.environ.get("BENCH_CHUNK_BUDGET", budget))
    lanes = int(os.environ.get("BENCH_LANES", lanes))
    decode_steps = int(os.environ.get("BENCH_DECODE_STEPS", decode_steps))

    params = llama.init_params(jax.random.PRNGKey(0), model_cfg)
    jax.block_until_ready(params)

    kw = dict(
        long_len=long_len, lanes=lanes, page=page, total_pages=total_pages,
        budget=budget, decode_steps=decode_steps, interpret=interpret,
        params=params,
    )
    arms = {}
    for chunked in (False, True):
        arms[chunked] = run_arm(chunked, model_cfg, **kw)
        print(
            json.dumps(
                {
                    "metric": "long_prompt_interference",
                    "arm": "chunked" if chunked else "unchunked",
                    "chunked_prefill_tokens": budget if chunked else None,
                    "long_len": long_len,
                    "lanes": lanes,
                    "model": mode,
                    "backend": jax.default_backend(),
                    **arms[chunked],
                }
            )
        )
    un, ch = arms[False], arms[True]
    if un["p90_itl_ms"] and ch["p90_itl_ms"]:
        print(
            json.dumps(
                {
                    "metric": "long_prompt_interference_comparison",
                    "p90_itl_improvement_x": round(
                        un["p90_itl_ms"] / ch["p90_itl_ms"], 2
                    ),
                    "throughput_ratio_chunked_over_unchunked": round(
                        ch["total_tok_s"] / max(un["total_tok_s"], 1e-9), 3
                    ),
                    "ttft_ratio_chunked_over_unchunked": (
                        round(ch["ttft_s"] / un["ttft_s"], 2)
                        if un.get("ttft_s") and ch.get("ttft_s")
                        else None
                    ),
                }
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
