"""MoE dispatch benchmark: routed (grouped ragged matmuls) vs masked-dense.

Measures, at the real Qwen3-30B-A3B expert geometry (128 experts, top-8,
hidden 2048, expert width 768, bf16), one MoE FFN layer:

- XLA cost-model FLOPs for both dispatches (the complexity-class claim:
  routed ~E/k lower), asserted >8x on TPU;
- wall time per call at prefill-shaped (batched tokens) and decode-shaped
  (few tokens) inputs, compile excluded.

Run on the chip: ``python benchmarking/bench_moe.py``; JSON line output.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    from llm_d_kv_cache_manager_tpu.models import llama
    from llm_d_kv_cache_manager_tpu.models.llama import _moe_mlp, init_params

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = dataclasses.replace(
            llama.QWEN3_30B_A3B, n_layers=1, vocab_size=1024
        )
        shapes = {"prefill": (1, 2048), "decode": (16, 1)}
        reps = 20
    else:  # CPU smoke: geometry only (ragged_dot lowers loop-dense on CPU)
        cfg = dataclasses.replace(
            llama.TINY_QWEN3_MOE, n_experts=16, n_experts_per_tok=4
        )
        shapes = {"prefill": (1, 64), "decode": (4, 1)}
        reps = 3

    dense_cfg = dataclasses.replace(cfg, moe_dispatch="dense")
    xla_cfg = dataclasses.replace(cfg, moe_gmm="xla")
    gmm_cfg = dataclasses.replace(cfg, moe_gmm="kernel")
    # BENCH_QUANT=int8: int8 EXPERT stacks (the opt-in path — the default
    # skips experts because this very benchmark showed the dequant doesn't
    # fuse into ragged_dot; results/moe_dispatch.md).
    quant = os.environ.get("BENCH_QUANT") or None
    params = init_params(
        jax.random.PRNGKey(0), cfg, quantize=quant, quantize_experts=bool(quant)
    )
    layer = params["layers"][0]
    rng = np.random.default_rng(0)

    for shape_name, (b, s) in shapes.items():
        x = jnp.asarray(
            rng.standard_normal((b, s, cfg.hidden_size)), cfg.dtype
        )
        row = {
            "metric": f"moe_dispatch_{shape_name}",
            "unit": "ms/call",
            "tokens": b * s,
            "n_experts": cfg.n_experts,
            "top_k": cfg.n_experts_per_tok,
            "quantize": quant,
            "backend": jax.default_backend(),
        }
        variants = (
            ("routed", xla_cfg),  # ragged_dot (rounds 1-3 baseline)
            ("gmm", gmm_cfg),  # Pallas grouped-matmul kernel (round 4)
            ("dense", dense_cfg),
        )
        outs = {}
        for name, c in variants:
            fn = jax.jit(lambda p, v, c=c: _moe_mlp(p, c, v))
            compiled = fn.lower(layer, x).compile()
            an = compiled.cost_analysis()
            an = an[0] if isinstance(an, list) else an
            outs[name] = np.asarray(fn(layer, x))  # warm + full fetch
            # Chain each call's output into the next input AND fence with a
            # device->host fetch: repeated identical dispatches can be
            # elided/overlapped by the runtime, and on the dev tunnel
            # block_until_ready returns before execution completes
            # (observed: "timings" 100x over hardware peak without these).
            # Take the MIN of several timing rounds: the shared dev tunnel
            # shows large sporadic stalls (same variant measured 8.8 ms and
            # 476 ms minutes apart); min-of-rounds is the defensible
            # device-time statistic under that noise.
            best = float("inf")
            for _ in range(3):
                y = x
                t0 = time.perf_counter()
                for _ in range(reps):
                    y = fn(layer, y)
                np.asarray(y[0, 0, :1])
                best = min(best, (time.perf_counter() - t0) / reps * 1e3)
            row[name + "_ms"] = round(best, 3)
            row[name + "_gflops"] = round(an.get("flops", 0) / 1e9, 3)
        row["value"] = row["gmm_ms"]
        row["gmm_speedup_vs_routed"] = round(row["routed_ms"] / row["gmm_ms"], 2)
        row["speedup_vs_dense"] = round(row["dense_ms"] / row["gmm_ms"], 2)
        # Effective grouped-matmul throughput (the 3 FFN matmuls' useful
        # FLOPs over the kernel's wall time).
        if row["routed_gflops"]:
            row["gmm_effective_tflops"] = round(
                row["routed_gflops"] / row["gmm_ms"], 1
            )
            row["flops_ratio_dense_over_routed"] = round(
                row["dense_gflops"] / row["routed_gflops"], 1
            )
        print(json.dumps(row))
        # On-chip numerics: the kernel must match the ragged_dot oracle
        # (interpret-mode tests can't catch Mosaic miscompiles — the
        # repo's own lesson, results/engine_throughput.md).
        scale = np.abs(outs["routed"].astype(np.float32)).max() + 1e-9
        err = (
            np.abs(
                outs["gmm"].astype(np.float32) - outs["routed"].astype(np.float32)
            ).max()
            / scale
        )
        tol = 5e-2 if quant else 2e-2
        assert err < tol, f"gmm-vs-ragged mismatch: rel err {err:.4f} ({shape_name})"
        if on_tpu and shape_name == "prefill":
            assert row["flops_ratio_dense_over_routed"] > 8, row
    return 0


if __name__ == "__main__":
    sys.exit(main())
