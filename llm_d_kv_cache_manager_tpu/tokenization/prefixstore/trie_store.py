"""Alternative prefix store: character trie (bounded).

Parity with reference ``pkg/tokenization/prefixstore/trie_store.go``: a
per-model character trie where each node records the tokens that become
fully contained once the prefix reaches that character (token ``[, high]``
byte offset ≤ the node's byte position). Lookup walks the prompt until the
first unseen character, collecting newly-contained tokens and the covered
ratio. Not the default: slower than the LRU store (reference
``docs/architecture.md:159-160``).

Design deviations from the reference (all three are fixes):

- nodes store *all* newly-contained token ids at their position rather than
  only the last one — the reference drops intermediate tokens when several
  (e.g. zero-width specials) become contained at the same character;
- each insert stamps its path with a generation, and lookups stop at the
  first generation change — the reference happily splices token indexes
  from different tokenizations that overwrote each other's shared-prefix
  nodes, returning corrupted sequences with full overlap ratio;
- growth is bounded (the reference grows without limit,
  ``trie_store.go`` has no eviction): per-model node count is capped at
  ``Config.trie_max_nodes`` by pruning stale-generation subtrees — which
  the generation rule above already makes unreachable to lookups, so the
  prune is lossless — then truncating the live path's tail if a single
  tokenization alone exceeds the budget; model tries are LRU-evicted
  beyond ``MAX_MODELS``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence

from .indexer import Config, Indexer, Offset


class _Node:
    __slots__ = ("children", "new_tokens", "last_index", "gen")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        # token ids newly contained at this node, and the index of the last
        # contained token in the full tokenization (-1 = none).
        self.new_tokens: list[int] = []
        self.last_index: int = -1
        # generation of the insert that last wrote this node. Every insert
        # rewrites a contiguous path from the root, so along any root path
        # generations are non-increasing; mixing nodes from different
        # generations would splice token indexes from different
        # tokenizations, so lookups stop at the first generation change.
        self.gen: int = 0


class ContainedTokenStore(Indexer):
    #: model tries kept; least-recently-used beyond this are dropped whole.
    MAX_MODELS = 64

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config()
        self._mu = threading.RLock()
        self._tries: OrderedDict[str, _Node] = OrderedDict()  # guarded_by: _mu
        self._counts: dict[str, int] = {}  # nodes per model  # guarded_by: _mu
        self._gen = 0  # guarded_by: _mu

    def _trie(self, model_name: str, create: bool) -> Optional[_Node]:  # kvlint: holds=_mu
        trie = self._tries.get(model_name)
        if trie is None and create:
            trie = _Node()
            self._tries[model_name] = trie
            self._counts[model_name] = 1
            while len(self._tries) > self.MAX_MODELS:
                evicted, _ = self._tries.popitem(last=False)
                del self._counts[evicted]
        if trie is not None:
            self._tries.move_to_end(model_name)
        return trie

    def node_count(self, model_name: str) -> int:
        """Nodes currently held for ``model_name`` (bounded diagnostics)."""
        with self._mu:
            return self._counts.get(model_name, 0)

    def _enforce_budget(self, model_name: str, root: _Node) -> None:  # kvlint: holds=_mu
        """Cap the model trie at ``config.trie_max_nodes`` nodes.

        First prune subtrees whose generation is stale: the lookup rule
        (stop at the first generation change from the root's) makes them
        unreachable already, so dropping them changes no lookup result.
        What survives is the single chain written by the latest insert; if
        that alone exceeds the budget, truncate its tail.
        """
        budget = max(2, self.config.trie_max_nodes)
        if self._counts[model_name] <= budget:
            return
        live_gen = root.gen
        node = root
        kept = 1
        while True:
            live = None
            for ch, child in node.children.items():
                if child.gen == live_gen:
                    live = (ch, child)
                    break  # one insert writes one path: ≤1 live child
            if live is None:
                node.children.clear()
                break
            if kept + 1 > budget:  # live path alone exceeds the budget
                node.children.clear()
                break
            node.children = {live[0]: live[1]}
            node = live[1]
            kept += 1
        self._counts[model_name] = kept

    def add_tokenization(
        self,
        model_name: str,
        prompt: str,
        tokens: Sequence[int],
        offsets: Sequence[Offset],
    ) -> None:
        if not prompt or not tokens:
            return
        if len(tokens) != len(offsets):
            raise ValueError("tokens and offsets must be parallel")

        with self._mu:
            self._gen += 1
            gen = self._gen
            node = self._trie(model_name, create=True)
            # Tokens contained before any character (zero-width specials at
            # position 0) attach to the root.
            k = -1
            root_new = []
            while k + 1 < len(tokens) and offsets[k + 1][1] <= 0:
                k += 1
                root_new.append(int(tokens[k]))
            node.new_tokens = root_new
            node.last_index = k
            node.gen = gen

            byte_pos = 0
            created = 0
            for ch in prompt:
                byte_pos += len(ch.encode("utf-8"))
                new_here: list[int] = []
                while k + 1 < len(tokens) and offsets[k + 1][1] <= byte_pos:
                    k += 1
                    new_here.append(int(tokens[k]))
                child = node.children.get(ch)
                if child is None:
                    child = _Node()
                    node.children[ch] = child
                    created += 1
                node = child
                node.new_tokens = new_here
                node.last_index = k
                node.gen = gen
            self._counts[model_name] += created
            self._enforce_budget(model_name, self._tries[model_name])

    def find_longest_contained_tokens(
        self, prompt: str, model_name: str
    ) -> tuple[list[int], float]:
        with self._mu:
            node = self._trie(model_name, create=False)
            if node is None or not prompt:
                return [], 0.0

            contained: list[int] = []
            expected_gen = node.gen  # root carries the latest insert's gen
            contained.extend(node.new_tokens)

            matched_chars = 0
            for ch in prompt:
                child = node.children.get(ch)
                if child is None or child.gen != expected_gen:
                    # gen change = this subpath was written by a different
                    # (older) tokenization than the nodes already collected;
                    # splicing them would corrupt the sequence.
                    break
                node = child
                matched_chars += 1
                contained.extend(node.new_tokens)
            return contained, matched_chars / len(prompt)
