"""HTTP scoring-API tests (aiohttp test client, mock tokenizer, no network).

Mirrors the reference online service surface (``online/main.go:238-363``)
incl. the chat-completions flow with an injected template (the reference
e2e does the same with a mock wrapper, ``e2e_test.go:227-358``).
"""

import asyncio
import socket

from aiohttp.test_utils import TestClient, TestServer

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import PodEntry
from llm_d_kv_cache_manager_tpu.server.api import ScoringService, ServiceConfig

from conftest import CharTokenizer

MODEL = "test-model"
TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>{{ message['content'] }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_service_scenario(scenario):
    """Start the service + aiohttp test client, run the async scenario."""
    service = ScoringService(
        ServiceConfig(block_size=4, zmq_endpoint=f"tcp://*:{_free_port()}"),
        tokenizer=CharTokenizer(),
    )
    service.start()

    async def runner():
        server = TestServer(service.build_app())
        client = TestClient(server)
        await client.start_server()
        try:
            await scenario(client, service)
        finally:
            await client.close()

    try:
        asyncio.run(runner())
    finally:
        service.shutdown()


def _warm(service, prompt, pod="tpu-pod-1"):
    keys = service.indexer.token_processor.tokens_to_kv_block_keys(
        [ord(c) for c in prompt], MODEL
    )
    service.indexer.kv_block_index.add(keys, [PodEntry(pod)])
    return keys


class TestScoreCompletions:
    def test_scores_warm_pod(self):
        async def scenario(c, service):
            prompt = "abcdefghijklmnop"
            _warm(service, prompt)
            resp = await c.post(
                "/score_completions", json={"prompt": prompt, "model": MODEL}
            )
            assert resp.status == 200
            assert (await resp.json())["scores"] == {"tpu-pod-1": 4}

        run_service_scenario(scenario)

    def test_cold_prompt_empty_scores(self):
        async def scenario(c, service):
            resp = await c.post(
                "/score_completions",
                json={"prompt": "something never seen here", "model": MODEL},
            )
            assert (await resp.json())["scores"] == {}

        run_service_scenario(scenario)

    def test_pod_filter(self):
        async def scenario(c, service):
            prompt = "abcdefgh"
            _warm(service, prompt, pod="pod-a")
            _warm(service, prompt, pod="pod-b")
            resp = await c.post(
                "/score_completions",
                json={"prompt": prompt, "model": MODEL, "pod_identifiers": ["pod-b"]},
            )
            assert (await resp.json())["scores"] == {"pod-b": 2}

        run_service_scenario(scenario)

    def test_validation_errors(self):
        async def scenario(c, service):
            resp = await c.post("/score_completions", json={"model": MODEL})
            assert resp.status == 400
            resp = await c.post("/score_completions", json={"prompt": "x"})
            assert resp.status == 400
            resp = await c.post(
                "/score_completions",
                data=b"not json",
                headers={"Content-Type": "application/json"},
            )
            assert resp.status == 400

        run_service_scenario(scenario)


class TestScoreChatCompletions:
    def test_renders_and_scores(self):
        async def scenario(c, service):
            messages = [
                {"role": "system", "content": "be brief"},
                {"role": "user", "content": "hi"},
            ]
            rendered = "<|system|>be brief<|user|>hi<|assistant|>"
            _warm(service, rendered)
            resp = await c.post(
                "/score_chat_completions",
                json={"messages": messages, "model": MODEL, "chat_template": TEMPLATE},
            )
            assert resp.status == 200
            data = await resp.json()
            assert data["rendered_prompt_chars"] == len(rendered)
            assert data["scores"] == {"tpu-pod-1": len(rendered) // 4}

        run_service_scenario(scenario)

    def test_validation(self):
        async def scenario(c, service):
            resp = await c.post("/score_chat_completions", json={"model": MODEL})
            assert resp.status == 400
            resp = await c.post(
                "/score_chat_completions", json={"messages": [], "model": MODEL}
            )
            assert resp.status == 400

        run_service_scenario(scenario)


class TestOps:
    def test_healthz(self):
        async def scenario(c, service):
            resp = await c.get("/healthz")
            assert resp.status == 200

        run_service_scenario(scenario)

    def test_metrics_exposition(self):
        async def scenario(c, service):
            await c.post(
                "/score_completions", json={"prompt": "abcdefgh", "model": MODEL}
            )
            resp = await c.get("/metrics")
            assert resp.status == 200
            assert "kvcache_index_lookup_requests_total" in (await resp.text())

        run_service_scenario(scenario)
