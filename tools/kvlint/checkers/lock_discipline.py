"""lock-discipline: guarded state stays under its lock; locks stay quick.

The convention (documented in ``docs/development.md``): shared mutable
attributes are annotated at their initialising assignment —

    self._views = {}  # guarded_by: _lock

Two checks follow:

1. every other ``self.<attr>`` touch of a guarded attribute must sit
   lexically inside ``with self.<lock>`` for the DECLARED lock.
   ``__init__`` is exempt (the object is not shared yet); a method whose
   ``def`` line carries ``# kvlint: holds=<lock>`` documents a
   caller-holds-the-lock contract and is treated as locked.
2. while any ``self.*lock*`` is held, calls that can block or stall the
   fleet — ``time.sleep``, ZMQ/socket ``recv``/``send_multipart``/
   ``connect``, and ``jax``/``jnp`` dispatch — are flagged: a sleep under
   a lock is a convoy, a device dispatch under a lock serialises the
   engine against every other thread.

The runtime companion (``utils/locktrace.py``) catches what static
lexing cannot: cross-thread acquisition-order cycles and unguarded
mutation observed live under ``LOCKTRACE=1``.
"""

from __future__ import annotations

import ast
import re
from typing import Optional, Union

from tools.kvlint.core import Finding, ModuleUnit, RepoContext

RULE = "lock-discipline"

_GUARDED_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=]*)?=.*#\s*guarded_by:\s*([\w|]+)"
)
_HOLDS_RE = re.compile(r"#\s*kvlint:\s*holds=(\w+)")

#: attribute-call names that block on I/O or a peer
_BLOCKING_ATTR_CALLS = {
    "sleep",
    "recv",
    "recv_multipart",
    "send_multipart",
    "accept",
    "connect",
}
#: module roots whose calls dispatch device work
_DISPATCH_ROOTS = {"jax", "jnp"}

_AnyFunc = Union[ast.FunctionDef, ast.AsyncFunctionDef]


def _is_lock_name(attr: str) -> bool:
    """Lock-ish attribute names in this tree: ``_lock``, ``mu``/``_mu``
    (the Go-parity modules), ``mutex``."""
    low = attr.lower()
    return (
        "lock" in low
        or "mutex" in low
        or low == "mu"
        or low.endswith("_mu")
    )


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _guarded_attrs(unit: ModuleUnit, cls: ast.ClassDef) -> dict[str, frozenset[str]]:
    """attr name -> acceptable lock attrs, from ``# guarded_by:`` comments
    inside the class's line span. ``guarded_by: _mu|_work`` declares
    aliases — e.g. a Condition built ON the mutex, either entry counts."""
    end = cls.end_lineno or cls.lineno
    out: dict[str, frozenset[str]] = {}
    for ln in range(cls.lineno, end + 1):
        m = _GUARDED_RE.search(unit.line_text(ln))
        if m:
            out[m.group(1)] = frozenset(m.group(2).split("|"))
    return out


def _held_at_def(unit: ModuleUnit, fn: _AnyFunc) -> set[str]:
    m = _HOLDS_RE.search(unit.line_text(fn.lineno))
    return {m.group(1)} if m else set()


class _MethodVisitor(ast.NodeVisitor):
    def __init__(
        self,
        unit: ModuleUnit,
        guarded: dict[str, frozenset[str]],
        held: set[str],
        lock_names: frozenset[str] = frozenset(),
    ) -> None:
        self.unit = unit
        self.guarded = guarded
        self.held = held
        #: names declared as guards (incl. aliases like a Condition) even
        #: when the attribute name itself is not lock-ish
        self.lock_names = lock_names
        self.findings: list[Finding] = []

    # -- lock tracking ----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        held_before = set(self.held)
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and (
                _is_lock_name(attr) or attr in self.lock_names
            ):
                self.held.add(attr)
        for stmt in node.body:
            self.visit(stmt)
        # Restore (not subtract): a nested ``with`` on an already-held lock
        # (RLock re-entrance, or inside a ``holds=`` method) must not clear
        # the outer hold for the code after the block.
        self.held = held_before
        # items themselves (e.g. ``with self._lock``) need no guard check

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    # -- guarded attribute touches ----------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.guarded:
            locks = self.guarded[attr]
            if not (locks & self.held):
                lock = "|".join(sorted(locks))
                access = (
                    "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                )
                self.findings.append(
                    Finding(
                        rule=RULE,
                        path=self.unit.rel,
                        line=node.lineno,
                        message=(
                            f"{access} of self.{attr} (guarded_by: {lock}) "
                            f"outside 'with self.{lock}' — unguarded "
                            "cross-thread access; hold the lock, annotate the "
                            f"method '# kvlint: holds={lock}' if the caller "
                            "holds it, or suppress with a justification"
                        ),
                    )
                )
        self.generic_visit(node)

    # -- blocking calls while a lock is held -------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            desc = self._blocking_desc(node.func)
            if desc is not None:
                locks = ", ".join(sorted(self.held))
                self.findings.append(
                    Finding(
                        rule=RULE,
                        path=self.unit.rel,
                        line=node.lineno,
                        message=(
                            f"{desc} while holding self.{locks} — blocking "
                            "under a lock convoys every other thread; move "
                            "the call outside the critical section"
                        ),
                    )
                )
        self.generic_visit(node)

    @staticmethod
    def _blocking_desc(fn: ast.expr) -> Optional[str]:
        if not isinstance(fn, ast.Attribute):
            return None
        root = fn.value
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            if root.id == "time" and fn.attr == "sleep":
                return "time.sleep()"
            if root.id in _DISPATCH_ROOTS:
                return f"{root.id}.{fn.attr}() dispatch"
        if fn.attr in _BLOCKING_ATTR_CALLS and not (
            isinstance(fn.value, ast.Name) and fn.value.id == "time"
        ):
            return f".{fn.attr}() (socket/ZMQ)"
        return None

    # nested defs inherit the current held set lexically, which is what a
    # closure invoked inline sees; closures stored for later are rare in
    # this tree and suppressible where they occur.


def check(unit: ModuleUnit, ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guarded = _guarded_attrs(unit, node)
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # __init__ runs before the object is shared across threads;
            # blocking-under-lock is still scanned there and everywhere.
            method_guarded = {} if fn.name == "__init__" else guarded
            lock_names = frozenset(n for alts in guarded.values() for n in alts)
            visitor = _MethodVisitor(
                unit, method_guarded, _held_at_def(unit, fn), lock_names
            )
            for stmt in fn.body:
                visitor.visit(stmt)
            findings.extend(visitor.findings)
    return findings
