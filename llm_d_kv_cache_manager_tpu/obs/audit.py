"""Routing-quality observability: index staleness + predicted-vs-realized.

The system's value proposition rests on two claims nothing measured until
this module existed: the global block index is *fresh enough* (KV events
become index-visible fast enough that scores reflect reality), and the
scorer's longest-prefix prediction is *accurate enough* (the pod really
serves the cache hits the scoreboard promised). Two trackers close the
loop, both off by default (``OBS_AUDIT``) with bit-identical legacy
behavior when unattached:

- ``StalenessTracker`` — event-plane lag. Every ``EventBatch`` carries its
  publish timestamp; on ingest the tracker records publish→apply lag per
  (pod, event type) (``kvcache_index_staleness_seconds``) and, from the
  subscriber's per-publisher seq numbers, how many events each pod's
  stream is behind (received-but-not-applied,
  ``kvcache_index_events_behind``).
- ``RouteAuditor`` — prediction vs reality. The router records each
  decision's predicted matched-block count and scoreboard keyed by
  request id; the pod reports the realized prefix-cache hit count back (a
  trailing-append ``RequestAudit`` KV event, or a direct call in-process).
  The join yields the realized/predicted ratio histogram
  (``kvcache_route_predicted_vs_realized_blocks``), a per-decision regret
  counterfactual (best scoreboard entry minus chosen,
  ``kvcache_route_regret_blocks``), and — when realized < predicted — a
  miss attribution (``kvcache_route_miss_attributed_total{cause}``):

  * ``dead_pod_reroute`` — the request landed on (or the fleet now
    considers) a different/unroutable pod than the one scored;
  * ``never_stored``    — the index never claimed the chain on that pod
    (the prediction came from affinity memory, not stored blocks);
  * ``stale_index``     — the scored entries are gone from the index now:
    the blocks were evicted after scoring and the prediction aged out;
  * ``evicted_on_pod``  — the index still claims the blocks but the pod's
    ground truth disagrees: the pod evicted them locally and the index
    has not caught up (phantom locality, repaired by events/resync);
  * ``quarantined``     — (KV_INTEGRITY, ISSUE 19) a block in the scored
    chain was revoked by a ``BadBlock`` event since the decision: the
    miss is the integrity plane doing its job (the pod refused to serve
    a corrupt page and recomputed), not index staleness — attributing it
    as ``evicted_on_pod`` would send an operator chasing phantom
    locality during a bad-block storm.

Since ISSUE 14 the join also carries the predicted-TTFT loop: decisions
made by the ROUTE_PREDICT latency model record their modeled TTFT, joins
from in-process callers carry the realized TTFT, and the resulting
realized/predicted ratio is observed
(``kvcache_route_ttft_realized_over_predicted``) and fed to the model's
``PredictionCorrector`` — the audit plane acting as an actuator, not
just a dashboard.

Wall clock on purpose throughout: event publish timestamps cross the wire
and are compared across hosts, so the comparison clock must be the same
wall clock (injectable for tests and the bench's virtual clocks).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..kvcache.metrics import collector
from ..utils import get_logger

log = get_logger("obs.audit")

#: shared histogram bucket upper bounds for staleness seconds (the last
#: implicit bucket is +Inf) — ZMQ-hop lag is ms-scale when healthy,
#: seconds-scale when the ingest pool is drowning.
STALENESS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

MISS_CAUSES = (
    "stale_index",
    "evicted_on_pod",
    "never_stored",
    "dead_pod_reroute",
    "quarantined",
)


def _percentile(samples: Sequence[float], q: float) -> Optional[float]:
    if not samples:
        return None
    s = sorted(samples)
    idx = min(int(q * len(s)), len(s) - 1)
    return s[idx]


class _LagHist:
    """Fixed-bucket histogram + count/sum/max (one per (pod, event))."""

    __slots__ = ("counts", "count", "sum", "max")

    def __init__(self):
        self.counts = [0] * (len(STALENESS_BUCKETS) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        for i, ub in enumerate(STALENESS_BUCKETS):
            if v <= ub:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.count += 1
        self.sum += v
        self.max = max(self.max, v)


class StalenessTracker:
    """Publish→index-visibility lag + events-behind, per pod.

    Attached to a ``KVEventsPool``: ``observe_received`` runs at enqueue
    (the subscriber-facing edge), ``observe_batch`` when a worker applies
    the batch. Unattached (the default) the pool touches nothing here.
    ``clock`` must be the same wall clock the publishers stamp batches
    with (``time.time`` in production; the bench injects its virtual
    clock).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        max_samples: int = 8192,
        shard: str = "",
    ):
        """``shard`` labels every metric observation this tracker makes:
        "" (the default) on a single index; the sharded control plane runs
        one tracker per scorer shard so a drowning ingest lane is visible
        per shard."""
        self._clock = clock
        self.shard = shard
        self._mu = threading.Lock()
        #: (pod, event_tag) -> _LagHist
        self._hists: dict[tuple[str, str], _LagHist] = {}  # guarded_by: _mu
        #: recent lag samples (bounded) for percentile summaries
        self._samples: deque = deque(maxlen=max_samples)  # guarded_by: _mu
        self._received: dict[str, int] = {}  # pod -> last seq enqueued  # guarded_by: _mu
        self._applied: dict[str, int] = {}  # pod -> last seq applied  # guarded_by: _mu
        self.events_observed = 0  # guarded_by: _mu
        self.max_lag_s = 0.0  # guarded_by: _mu

    # -- pool-side observations ---------------------------------------------
    def observe_received(self, pod: str, seq: int) -> None:
        with self._mu:
            prev = self._received.get(pod)
            if prev is None:
                # Seed the applied high-water one below the first seq seen,
                # so enqueued-but-never-applied batches read as behind from
                # the start — a cold-start backlog (subscriber enqueuing a
                # storm the shard worker hasn't touched) must not read 0.
                self._applied.setdefault(pod, seq - 1)
            if prev is None or seq > prev:
                self._received[pod] = seq

    def observe_batch(
        self, pod: str, seq: int, publish_ts: float, event_tags: Sequence[str]
    ) -> None:
        """One decoded batch applied to the index: record publish→apply
        lag once per contained event, labeled by event type. ``ts <= 0``
        (legacy publishers that stamp nothing) records nothing — a bogus
        epoch delta would bury every real sample."""
        lag = self._clock() - publish_ts if publish_ts > 0 else None
        with self._mu:
            prev = self._applied.get(pod)
            if prev is None or seq > prev:
                self._applied[pod] = seq
            if lag is None:
                return
            lag = max(lag, 0.0)
            for tag in event_tags:
                self._hists.setdefault((pod, tag), _LagHist()).observe(lag)
                self.events_observed += 1
            self._samples.append(lag)
            self.max_lag_s = max(self.max_lag_s, lag)
        for tag in event_tags:
            collector.observe_staleness(pod, tag, lag, self.shard)

    # -- read side -----------------------------------------------------------
    def events_behind(self) -> dict[str, int]:
        """Per pod: events enqueued but not yet applied (subscriber seq
        high-water minus worker high-water). Mirrored into the
        ``kvcache_index_events_behind`` gauge by the caller's scrape."""
        with self._mu:
            out = {
                pod: max(seq - self._applied.get(pod, seq), 0)
                for pod, seq in self._received.items()
            }
        for pod, behind in out.items():
            collector.set_events_behind(pod, behind, self.shard)
        return out

    def percentiles(self, qs=(0.5, 0.99)) -> dict[str, Optional[float]]:
        with self._mu:
            samples = list(self._samples)
        return {f"p{int(q * 100)}": _percentile(samples, q) for q in qs}

    def snapshot(self) -> dict:
        """Compact summary for ``/stats``."""
        with self._mu:
            events = self.events_observed
            max_lag = self.max_lag_s
            samples = list(self._samples)
        return {
            "events_observed": events,
            "max_lag_s": round(max_lag, 6),
            "p50_lag_s": _percentile(samples, 0.5),
            "p99_lag_s": _percentile(samples, 0.99),
            "events_behind": self.events_behind(),
        }

    def detail(self) -> dict:
        """Full per-(pod, event) histograms for ``/debug/staleness``."""
        with self._mu:
            per = {
                f"{pod}/{tag}": {
                    "count": h.count,
                    "sum_s": round(h.sum, 6),
                    "max_s": round(h.max, 6),
                    "buckets": dict(
                        zip([str(b) for b in STALENESS_BUCKETS] + ["+Inf"], h.counts)
                    ),
                }
                for (pod, tag), h in self._hists.items()
            }
        return {
            "bucket_bounds_s": list(STALENESS_BUCKETS),
            "per_pod_event": per,
            **self.snapshot(),
        }


class MergedStaleness:
    """Read-side view over the sharded plane's per-shard trackers: the
    same ``events_behind``/``percentiles``/``snapshot``/``detail`` surface
    a single ``StalenessTracker`` offers, aggregated. Per-pod events-behind
    is the MAX across shard lanes (one event pending on three shards is
    one event behind, on the worst lane) PLUS the plane's admission-edge
    backlog (``admission``: batches admitted but not yet decoded/split —
    a lane's received high-water only advances at dispatch, so a drowning
    decode stage would otherwise read as quiet lanes); lag percentiles
    pool every shard's samples."""

    def __init__(
        self,
        trackers: Sequence[StalenessTracker],
        admission: Optional[Callable[[], dict]] = None,
    ):
        self.trackers = list(trackers)
        self.admission = admission

    def events_behind(self) -> dict[str, int]:
        merged: dict[str, int] = {}
        for t in self.trackers:
            for pod, behind in t.events_behind().items():
                merged[pod] = max(merged.get(pod, 0), behind)
        if self.admission is not None:
            for pod, behind in self.admission().items():
                merged[pod] = merged.get(pod, 0) + behind
                # the plane-level total rides the "" shard series (the
                # per-lane series carry their own shard labels)
                collector.set_events_behind(pod, merged[pod], "")
        return merged

    def _all_samples(self) -> list[float]:
        samples: list[float] = []
        for t in self.trackers:
            with t._mu:
                samples.extend(t._samples)
        return samples

    def percentiles(self, qs=(0.5, 0.99)) -> dict[str, Optional[float]]:
        samples = self._all_samples()
        return {f"p{int(q * 100)}": _percentile(samples, q) for q in qs}

    def snapshot(self) -> dict:
        samples = self._all_samples()
        return {
            "events_observed": sum(t.events_observed for t in self.trackers),
            "max_lag_s": round(max((t.max_lag_s for t in self.trackers), default=0.0), 6),
            "p50_lag_s": _percentile(samples, 0.5),
            "p99_lag_s": _percentile(samples, 0.99),
            "events_behind": self.events_behind(),
        }

    def detail(self) -> dict:
        return {
            "shards": {t.shard: t.detail() for t in self.trackers},
            **self.snapshot(),
        }


@dataclass
class AuditRecord:
    """One joined decision/outcome pair (the ``/debug/audit`` row)."""

    request_id: str
    chosen_pod: str
    realized_pod: str
    predicted_blocks: int
    realized_blocks: int
    decision: str
    regret_blocks: int
    #: realized/predicted; None when predicted == 0 (nothing promised)
    ratio: Optional[float]
    #: miss attribution; None when realized >= predicted
    cause: Optional[str]
    trace_id: Optional[str] = None
    #: wall-clock timestamps (decision / join) — display only
    decided_at: float = 0.0
    joined_at: float = 0.0
    #: predicted-TTFT routing (ROUTE_PREDICT): the latency model's
    #: per-decision claim, the realized TTFT the pod measured, and their
    #: realized/predicted ratio — None on legacy (score-max) decisions,
    #: and the row keys are then absent so knobs-off /debug/audit rows
    #: stay bit-identical
    predicted_ttft_s: Optional[float] = None
    realized_ttft_s: Optional[float] = None
    ttft_ratio: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "chosen_pod": self.chosen_pod,
            "realized_pod": self.realized_pod,
            "predicted_blocks": self.predicted_blocks,
            "realized_blocks": self.realized_blocks,
            "decision": self.decision,
            "regret_blocks": self.regret_blocks,
            "ratio": self.ratio,
            "cause": self.cause,
            "trace_id": self.trace_id,
            "decided_at": self.decided_at,
            "joined_at": self.joined_at,
            **(
                {
                    "predicted_ttft_s": self.predicted_ttft_s,
                    "realized_ttft_s": self.realized_ttft_s,
                    "ttft_ratio": self.ttft_ratio,
                }
                if self.predicted_ttft_s is not None
                else {}
            ),
        }


@dataclass
class _Pending:
    chosen_pod: str
    predicted_blocks: int
    #: the index's own claim at decision time (0 = prediction came from
    #: affinity memory — the ``never_stored`` discriminator)
    index_blocks: int
    scoreboard: dict
    decision: str
    regret_blocks: int
    chain_hashes: tuple
    model: str
    trace_id: Optional[str]
    decided_at: float
    #: the latency model's TTFT claim (ROUTE_PREDICT); None = legacy
    predicted_ttft_s: Optional[float] = None


class RouteAuditor:
    """Joins routing decisions with realized prefix-cache hits.

    ``index``/``fleet_health`` (both optional) power the miss attribution:
    the index is re-probed at join time for the chain the decision scored,
    and fleet health answers "was the pod even routable". Without them the
    attribution degrades gracefully (every eviction-flavored miss reads
    ``stale_index``).
    """

    def __init__(
        self,
        index=None,
        fleet_health=None,
        model_name: str = "",
        ring: int = 2048,
        pending_cap: int = 4096,
        max_chain_hashes: int = 512,
        clock: Callable[[], float] = time.time,
        ttft_corrector=None,
    ):
        """``ttft_corrector`` (optional, a
        ``kvcache.predictor.PredictionCorrector`` — wired by
        ``ROUTE_PREDICT``): joins that carry BOTH a predicted and a
        realized TTFT feed it the outcome, closing the routing model's
        feedback loop — the audit plane acting as an actuator. The feed
        is skipped when the request landed on a different pod than the
        one predicted for (the outcome is not that pod's model error).
        None (default) = observation-only, legacy behavior."""
        self.index = index
        self.fleet_health = fleet_health
        self.model_name = model_name
        self.ttft_corrector = ttft_corrector
        self.max_chain_hashes = max_chain_hashes
        self._clock = clock
        self._mu = threading.Lock()
        self._pending: "OrderedDict[str, _Pending]" = OrderedDict()  # guarded_by: _mu
        self._pending_cap = pending_cap
        self._ring: deque = deque(maxlen=max(ring, 1))  # guarded_by: _mu
        self.decisions_recorded = 0  # guarded_by: _mu
        self.joined = 0  # guarded_by: _mu
        self.unmatched_realized = 0  # guarded_by: _mu
        self.pending_evicted = 0  # guarded_by: _mu
        self.miss_causes = dict.fromkeys(MISS_CAUSES, 0)  # guarded_by: _mu
        #: recently revoked block hashes (BadBlock events; bounded — the
        #: attribution window only needs "was this chain hit by a recent
        #: revocation", not a durable ledger)
        self._bad_blocks: "OrderedDict[int, None]" = OrderedDict()  # guarded_by: _mu
        self._bad_blocks_cap = 4096

    # -- decision side (router/scorer) ---------------------------------------
    def record_decision(
        self,
        request_id: str,
        *,
        chosen_pod: str,
        predicted_blocks: int,
        scoreboard: Optional[dict] = None,
        index_blocks: Optional[int] = None,
        decision: str = "route_warm",
        chain_hashes: Sequence[int] = (),
        model: Optional[str] = None,
        trace_id: Optional[str] = None,
        predicted_ttft_s: Optional[float] = None,
    ) -> None:
        """Record what the scorer promised for ``request_id``. ``scoreboard``
        is the top-k pod→score map the decision saw; regret = the best
        entry minus the chosen entry (how much warmth the placement left
        on the table, 0 when the warmest pod was picked)."""
        scoreboard = dict(scoreboard or {})
        best = max(scoreboard.values(), default=0)
        regret = max(best - scoreboard.get(chosen_pod, 0), 0)
        rec = _Pending(
            chosen_pod=chosen_pod,
            predicted_blocks=int(predicted_blocks),
            index_blocks=(
                int(index_blocks)
                if index_blocks is not None
                else int(predicted_blocks)
            ),
            scoreboard=scoreboard,
            decision=decision,
            regret_blocks=regret,
            chain_hashes=tuple(chain_hashes)[: self.max_chain_hashes],
            model=model if model is not None else self.model_name,
            trace_id=trace_id,
            decided_at=self._clock(),
            predicted_ttft_s=predicted_ttft_s,
        )
        with self._mu:
            self._pending[request_id] = rec
            self._pending.move_to_end(request_id)
            self.decisions_recorded += 1
            while len(self._pending) > self._pending_cap:
                self._pending.popitem(last=False)
                self.pending_evicted += 1
        collector.observe_route_regret(decision, regret)

    # -- realized side (pod report via RequestAudit event or in-process) ----
    def record_realized(
        self,
        request_id: str,
        pod: str,
        realized_blocks: int,
        realized_ttft_s: Optional[float] = None,
    ) -> Optional[AuditRecord]:
        """Join the pod's ground truth with the pending decision. Returns
        the joined record (also ring-buffered for ``/debug/audit``), or
        None when no decision was recorded for this request id.
        ``realized_ttft_s`` (in-process callers only — the RequestAudit
        wire event carries blocks, not latency) additionally joins the
        predicted-TTFT claim: the realized/predicted latency ratio is
        observed and, when a corrector is attached, fed back to the
        routing model."""
        with self._mu:
            rec = self._pending.pop(request_id, None)
            if rec is None:
                self.unmatched_realized += 1
                return None
        realized_blocks = int(realized_blocks)
        predicted = rec.predicted_blocks
        ratio = (realized_blocks / predicted) if predicted > 0 else None
        cause = None
        if predicted > 0 and realized_blocks < predicted:
            cause = self._attribute(rec, pod)
            collector.observe_miss_cause(cause)
        if ratio is not None:
            collector.observe_predicted_vs_realized(ratio)
        ttft_ratio = None
        if (
            rec.predicted_ttft_s is not None
            and rec.predicted_ttft_s > 0
            and realized_ttft_s is not None
            and pod == rec.chosen_pod
        ):
            # Only the pod the model predicted FOR can judge the model:
            # a rerouted request's latency has another pod's denominator
            # and would pollute the honesty histogram exactly when the
            # prediction was never followed. (The row still records
            # realized_ttft_s for the reroute, just no ratio.)
            ttft_ratio = realized_ttft_s / rec.predicted_ttft_s
            collector.observe_ttft_ratio(ttft_ratio)
            if self.ttft_corrector is not None:
                self.ttft_corrector.observe(
                    pod, rec.predicted_ttft_s, realized_ttft_s
                )
        audit = AuditRecord(
            request_id=request_id,
            chosen_pod=rec.chosen_pod,
            realized_pod=pod,
            predicted_blocks=predicted,
            realized_blocks=realized_blocks,
            decision=rec.decision,
            regret_blocks=rec.regret_blocks,
            ratio=round(ratio, 4) if ratio is not None else None,
            cause=cause,
            trace_id=rec.trace_id,
            decided_at=rec.decided_at,
            joined_at=self._clock(),
            predicted_ttft_s=rec.predicted_ttft_s,
            realized_ttft_s=realized_ttft_s,
            ttft_ratio=(
                round(ttft_ratio, 4) if ttft_ratio is not None else None
            ),
        )
        with self._mu:
            self.joined += 1
            if cause is not None:
                self.miss_causes[cause] += 1
            self._ring.append(audit)
        return audit

    def observe_bad_block(self, block_hashes: Sequence[int]) -> None:
        """A ``BadBlock`` revocation reached the scorer: remember the
        hashes (bounded FIFO) so a subsequent realized-miss on a chain
        containing one attributes as ``quarantined`` rather than
        ``evicted_on_pod`` — the eviction was deliberate poison control,
        not index rot."""
        with self._mu:
            for h in block_hashes:
                self._bad_blocks[int(h)] = None
            while len(self._bad_blocks) > self._bad_blocks_cap:
                self._bad_blocks.popitem(last=False)

    def _attribute(self, rec: _Pending, realized_pod: str) -> str:
        """Classify one miss using current index + fleet-health state (see
        the module docstring for the causes)."""
        fh = self.fleet_health
        if realized_pod != rec.chosen_pod or (
            fh is not None and not fh.is_routable(rec.chosen_pod)
        ):
            return "dead_pod_reroute"
        if rec.chain_hashes:
            with self._mu:
                if any(h in self._bad_blocks for h in rec.chain_hashes):
                    # A revocation hit the scored chain after the decision:
                    # the pod quarantined a corrupt copy and recomputed —
                    # checked before the index probes because the BadBlock
                    # eviction makes those read as stale/evicted too.
                    return "quarantined"
        if rec.index_blocks <= 0:
            # The index never claimed the chain on this pod — the
            # prediction came from affinity memory (or a wiped index).
            return "never_stored"
        current = self._probe(rec)
        if current is None or current < rec.index_blocks:
            # The scored entries are gone from the index too: evicted
            # after scoring — the prediction was honest when made.
            return "stale_index"
        # The index STILL advertises the blocks the pod says it lacks:
        # the pod evicted locally and the index has not caught up.
        return "evicted_on_pod"

    def _probe(self, rec: _Pending) -> Optional[int]:
        """Longest consecutive prefix of the decision's chain the index
        currently holds for the chosen pod; None when unprobeable (no
        index attached / no stored hashes / probe error)."""
        if self.index is None or not rec.chain_hashes:
            return None
        try:
            from ..kvcache.kvblock.keys import Key

            keys = [Key(rec.model, h) for h in rec.chain_hashes]
            hits = self.index.lookup(keys, {rec.chosen_pod})
            n = 0
            for key in keys:
                if rec.chosen_pod not in (hits.get(key) or []):
                    break
                n += 1
            return n
        except Exception:
            log.exception("audit index probe failed")
            return None

    # -- read side -----------------------------------------------------------
    def recent(
        self,
        limit: int = 50,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> list[dict]:
        with self._mu:
            rows = list(self._ring)
        if request_id is not None:
            rows = [r for r in rows if r.request_id == request_id]
        if trace_id is not None:
            rows = [r for r in rows if r.trace_id == trace_id]
        # The Tracer limit contract: limit <= 0 returns nothing. (The old
        # `rows[-max(limit, 0):]` slice returned EVERYTHING at limit=0 —
        # the one debug surface that inverted the contract.)
        if limit <= 0:
            return []
        return [r.to_dict() for r in rows[-limit:]]

    def snapshot(self) -> dict:
        with self._mu:
            ratios = [r.ratio for r in self._ring if r.ratio is not None]
            ttft_ratios = [
                r.ttft_ratio for r in self._ring if r.ttft_ratio is not None
            ]
            return {
                "decisions_recorded": self.decisions_recorded,
                "joined": self.joined,
                "pending": len(self._pending),
                "pending_evicted": self.pending_evicted,
                "unmatched_realized": self.unmatched_realized,
                "miss_causes": dict(self.miss_causes),
                "recent_ratio_p50": _percentile(ratios, 0.5),
                # Key appears only once a predicted-TTFT join happened:
                # knobs-off audit snapshots keep their legacy field set.
                **(
                    {"ttft_ratio_p50": _percentile(ttft_ratios, 0.5)}
                    if ttft_ratios
                    else {}
                ),
            }


def _cap_per_pod_event(detail: dict, limit: int) -> dict:
    """Apply the Tracer limit contract to a ``detail()`` payload: cap the
    per-(pod, event) histogram rows (the only unbounded-in-fleet-size
    part) at ``limit``, recursing into per-shard details for the merged
    view. Sorted keys so the same limit always keeps the same rows."""
    out = dict(detail)
    if "per_pod_event" in out:
        rows = out["per_pod_event"]
        out["per_pod_event"] = {
            k: rows[k] for k in sorted(rows)[: max(limit, 0)]
        }
    if "shards" in out:
        out["shards"] = {
            shard: _cap_per_pod_event(d, limit)
            for shard, d in out["shards"].items()
        }
    return out


def debug_staleness_payload(
    tracker: Optional[StalenessTracker], query
) -> tuple[int, dict]:
    """``GET /debug/staleness`` body (the endpoint is always routable;
    with the knob off it reports itself disabled, like /debug/traces).
    ``?limit=`` caps the per-(pod, event) histogram rows with the Tracer
    contract (``limit <= 0`` returns nothing); tolerant 400 on a bad
    limit."""
    if tracker is None:
        return 200, {"enabled": False}
    try:
        limit = int(query.get("limit", "50"))
    except ValueError:
        return 400, {"error": "invalid limit (want an int)"}
    return 200, {
        "enabled": True,
        **_cap_per_pod_event(tracker.detail(), limit),
    }


def debug_audit_payload(
    auditor: Optional[RouteAuditor], query
) -> tuple[int, dict]:
    """``GET /debug/audit`` body: recent joined audits, filterable by
    ``?request_id=`` / ``?trace_id=``; tolerant 400 on a bad limit."""
    if auditor is None:
        return 200, {"enabled": False, "audits": []}
    try:
        limit = int(query.get("limit", "50"))
    except ValueError:
        return 400, {"error": "invalid limit"}
    return 200, {
        "enabled": True,
        "audits": auditor.recent(
            limit=limit,
            request_id=query.get("request_id"),
            trace_id=query.get("trace_id"),
        ),
        **auditor.snapshot(),
    }
