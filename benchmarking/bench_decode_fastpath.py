"""Decode fast path microbenchmark (ISSUE 7): where does step time go?

Three measurements on the real stack, one JSON line each:

- **decode arm** — a single engine decodes a fixed token budget with the
  fast path off vs on (``decode_fused_sampling`` + ``decode_pipeline``),
  reporting tok/s and the step-phase decomposition
  (schedule/prefill/decode/sample/gather/publish). The fusion evidence is
  the ``sample`` phase: the blocking share of the sampled-token
  device_get, which the fast path's async D2H + device-resident chaining
  collapses to ~0.
- **spec arm** — the same engine with ``spec_decode="prompt_lookup"`` on
  an EXTRACTIVE workload (the prompt repeats an n-gram pattern, the
  regime prompt lookup exists for), reporting acceptance rate and tok/s.
- **pull arm** — a 2-pod ZMQ fleet: the cold pod is mid-decode on an
  unrelated request when a pull-routed request arrives (``ASYNC_PULL``);
  the reported ``hidden_s``/``exposed_s`` split (from the pull-overlap
  decomposition) shows how much of the transfer the decode work hid.

Env knobs: BENCH_FASTPATH_TOKENS (decode budget per sequence, default
48), BENCH_FASTPATH_LANES (decode lanes, default 4).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _engine_cfg(**kw):
    from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
    from llm_d_kv_cache_manager_tpu.server import (
        BlockManagerConfig,
        EngineConfig,
        SchedulerConfig,
    )

    kw.setdefault("scheduler", SchedulerConfig(max_prefill_batch=4))
    import jax

    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=256, page_size=4),
        max_model_len=128,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=jax.default_backend() != "tpu",
        **kw,
    )


def decode_arm(max_new: int, lanes: int) -> dict:
    from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
    from llm_d_kv_cache_manager_tpu.server import Engine, SamplingParams

    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, TINY_LLAMA.vocab_size, 12).tolist() for _ in range(lanes)
    ]
    out = {}
    outputs = {}
    for label, kw in (
        ("legacy", {}),
        ("fastpath", dict(decode_fused_sampling=True, decode_pipeline=True)),
    ):
        eng = Engine(_engine_cfg(**kw))
        # Warm the jit caches so the measured pass is steady-state — TWO
        # rounds, because the measured pass prefills warm (cached-prefix)
        # shapes: a single cold round would leave the warm-prefill
        # executable to compile inside whichever arm runs first and
        # poison the A/B.
        for _ in range(2):
            for p in prompts:
                eng.add_request(p, SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        eng.obs_step_timing = True
        seqs = [
            eng.add_request(p, SamplingParams(max_new_tokens=max_new))
            for p in prompts
        ]
        t0 = time.perf_counter()
        eng.run_until_complete()
        wall = time.perf_counter() - t0
        toks = sum(s.num_generated for s in seqs)
        outputs[label] = [s.generated_tokens for s in seqs]
        out[label] = {
            "tok_s": round(toks / wall, 2),
            "wall_s": round(wall, 3),
            "phases": {
                k: round(v, 4) if isinstance(v, float) else v
                for k, v in eng.step_stats.items()
            },
        }
    assert outputs["legacy"] == outputs["fastpath"], "greedy parity violated"
    out["speedup"] = round(out["fastpath"]["tok_s"] / out["legacy"]["tok_s"], 3)
    out["sample_s_legacy"] = out["legacy"]["phases"]["sample_s"]
    out["sample_s_fastpath"] = out["fastpath"]["phases"]["sample_s"]
    return out


def spec_arm(max_new: int) -> dict:
    """Prompt-lookup speculation on an extractive prompt: the context
    repeats a short token pattern, so proposals echo the prompt and
    acceptance is non-trivial (random-token workloads would pin it at 0)."""
    from llm_d_kv_cache_manager_tpu.server import Engine, SamplingParams

    pattern = [11, 23, 42, 7, 99, 5, 64, 31]
    prompt = (pattern * 6)[:44]  # repeated n-grams: lookup's home turf
    out = {}
    for label, kw in (
        ("plain", {}),
        ("spec", dict(spec_decode="prompt_lookup", spec_k=4)),
    ):
        eng = Engine(_engine_cfg(**kw))
        eng.add_request(list(prompt), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()  # warm jit caches
        seq = eng.add_request(list(prompt), SamplingParams(max_new_tokens=max_new))
        t0 = time.perf_counter()
        eng.run_until_complete()
        wall = time.perf_counter() - t0
        out[label] = {
            "tok_s": round(seq.num_generated / wall, 2),
            "tokens": seq.generated_tokens,
        }
        if label == "spec":
            st = eng.spec_stats
            out["acceptance_rate"] = (
                round(st["accepted"] / st["proposed"], 4)
                if st["proposed"]
                else None
            )
            out["proposed"] = st["proposed"]
            out["accepted"] = st["accepted"]
            out["bursts"] = st["bursts"]
    assert out["plain"]["tokens"] == out["spec"]["tokens"], "spec parity violated"
    for label in ("plain", "spec"):
        del out[label]["tokens"]
    return out


def pull_arm() -> dict:
    """Async-pull overlap on a live 2-pod fleet: the cold pod is decoding
    an unrelated request when the pull-routed one arrives, so the fetch
    rides under real decode compute."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests")
    )
    from conftest import free_tcp_port

    from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
    from llm_d_kv_cache_manager_tpu.server import SamplingParams
    from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

    def pod(pod_id, **kw):
        return PodServer(
            PodServerConfig(
                model_name="tiny-llama",
                pod_identifier=pod_id,
                publish_events=False,
                engine=_engine_cfg(),
                **kw,
            )
        )

    rng = np.random.default_rng(11)
    endpoint = f"tcp://127.0.0.1:{free_tcp_port()}"
    warm = pod("fp-warm", transfer_endpoint=endpoint)
    cold = pod("fp-cold", async_pull=True, obs_metrics=True)
    warm.start(), cold.start()
    try:
        prefix = rng.integers(0, TINY_LLAMA.vocab_size, 32).tolist()
        warm.generate(prefix, SamplingParams(max_new_tokens=2), timeout=300)
        # A full prefill batch queued AHEAD of the pull-routed request:
        # in the blocking world the pull would run before submission and
        # the request would then STILL wait behind these — the async
        # import instead rides under exactly that queue wait (the hidden
        # share below).
        fillers = [
            cold.submit(
                rng.integers(0, TINY_LLAMA.vocab_size, 24).tolist(),
                SamplingParams(max_new_tokens=2),
            )
            for _ in range(4)
        ]
        t0 = time.perf_counter()
        pulled = cold.submit(
            prefix + rng.integers(0, TINY_LLAMA.vocab_size, 4).tolist(),
            SamplingParams(max_new_tokens=4),
            pull_source=endpoint,
        )
        s = pulled.result(timeout=300)
        pull_to_done = time.perf_counter() - t0
        for f in fillers:
            f.result(timeout=300)
        text = (cold.metrics.exposition() or b"").decode()
        hidden = exposed = None
        for line in text.splitlines():
            if line.startswith("kvcache_transfer_pull_overlap_seconds_sum"):
                val = round(float(line.rsplit(" ", 1)[1]), 4)
                if 'kind="hidden"' in line:
                    hidden = val
                elif 'kind="exposed"' in line:
                    exposed = val
        return {
            "imported_blocks": s.num_cached_prompt // 4,
            "cached_prompt_tokens": s.num_cached_prompt,
            "request_wall_s": round(pull_to_done, 3),
            "hidden_s": hidden,
            "exposed_s": exposed,
        }
    finally:
        warm.shutdown(), cold.shutdown()


def main() -> int:
    max_new = int(os.environ.get("BENCH_FASTPATH_TOKENS", "48"))
    lanes = int(os.environ.get("BENCH_FASTPATH_LANES", "4"))
    import jax

    print(
        json.dumps({"arm": "decode", "backend": jax.default_backend(),
                    **decode_arm(max_new, lanes)})
    )
    print(json.dumps({"arm": "spec", **spec_arm(max_new)}))
    print(json.dumps({"arm": "pull", **pull_arm()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
