"""End-to-end read-path tests for KVCacheIndexer (no network): mirrors the
reference e2e suite's CacheHit/CacheMiss/PrefixReduction scenarios
(``tests/e2e/redis_mock/e2e_test.go``) with a mock tokenizer."""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache import KVCacheIndexer, KVCacheIndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    DeviceTier,
    PodEntry,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore import Config as PSConfig
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore import LRUTokenStore
from llm_d_kv_cache_manager_tpu.kvcache.indexer import KVCacheIndexerConfig
from llm_d_kv_cache_manager_tpu.tokenization.pool import TokenizationPoolConfig

from conftest import CharTokenizer

MODEL = "test-model"
BLOCK = 4  # small token block size, like the reference e2e (block size 4)


@pytest.fixture
def indexer():
    cfg = KVCacheIndexerConfig(
        token_processor=TokenProcessorConfig(block_size=BLOCK),
        tokenization_pool=TokenizationPoolConfig(workers_count=2),
    )
    ix = KVCacheIndexer(cfg, tokenizer=CharTokenizer(), prefix_store=LRUTokenStore(PSConfig(block_size=4)))
    ix.run()
    yield ix
    ix.shutdown()


def _prompt_to_keys(indexer, prompt):
    tokens = [ord(c) for c in prompt]
    return indexer.token_processor.tokens_to_kv_block_keys(tokens, MODEL)


class TestReadPath:
    def test_cache_miss_scores_empty(self, indexer):
        scores = indexer.get_pod_scores("hello world padded!!", MODEL)
        assert scores == {}

    def test_cache_hit_scores_pod(self, indexer):
        prompt = "abcdefghijklmnop"  # 4 blocks of 4 tokens
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-1", DeviceTier.TPU_HBM)])
        scores = indexer.get_pod_scores(prompt, MODEL)
        assert scores == {"pod-1": 4}

    def test_prefix_reduction(self, indexer):
        prompt = "abcdefghijklmnop"
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-1")])
        # Evict the last two blocks → score drops to 2.
        for key in keys[2:]:
            indexer.kv_block_index.evict(key, [PodEntry("pod-1")])
        scores = indexer.get_pod_scores(prompt, MODEL)
        assert scores == {"pod-1": 2}

    def test_prefix_expansion_longer_prompt(self, indexer):
        short = "abcdefgh"  # 2 blocks
        longer = short + "ijklmnop"  # 4 blocks
        indexer.kv_block_index.add(_prompt_to_keys(indexer, short), [PodEntry("pod-1")])
        scores = indexer.get_pod_scores(longer, MODEL)
        assert scores == {"pod-1": 2}

    def test_pod_filter(self, indexer):
        prompt = "abcdefgh"
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-1"), PodEntry("pod-2")])
        scores = indexer.get_pod_scores(prompt, MODEL, pod_identifiers=["pod-2"])
        assert scores == {"pod-2": 2}

    def test_two_pods_different_depths(self, indexer):
        prompt = "abcdefghijklmnop"
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-deep")])
        indexer.kv_block_index.add(keys[:1], [PodEntry("pod-shallow")])
        scores = indexer.get_pod_scores(prompt, MODEL)
        assert scores == {"pod-deep": 4, "pod-shallow": 1}

    def test_short_prompt_no_blocks(self, indexer):
        assert indexer.get_pod_scores("ab", MODEL) == {}

    def test_score_tokens_matches_get_pod_scores(self, indexer):
        prompt = "abcdefgh"
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-1")])
        via_prompt = indexer.get_pod_scores(prompt, MODEL)
        via_tokens = indexer.score_tokens([ord(c) for c in prompt], MODEL)
        assert via_prompt == via_tokens == {"pod-1": 2}

    def test_long_prefix(self, indexer):
        # ~4.5k-token analogue of the reference LongPrefix e2e test.
        prompt = ("abcdefghijklmnopqrstuvwxyz" * 200)[:4500]
        keys = _prompt_to_keys(indexer, prompt)
        indexer.kv_block_index.add(keys, [PodEntry("pod-1")])
        scores = indexer.get_pod_scores(prompt, MODEL)
        assert scores == {"pod-1": len(keys)}
        assert len(keys) == 4500 // BLOCK
