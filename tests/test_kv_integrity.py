"""End-to-end KV-block integrity suite (ISSUE 19 acceptance).

- **Digest unit**: chained crc32 over the exact stored/wire bytes
  (scales included), order-sensitive, deterministic.
- **BlockIntegrity**: record/check outcomes (ok / corrupt / unverified —
  absence of evidence never truncates), quarantine bookkeeping, LRU
  table cap, thread-safe snapshot.
- **Corruption drills**, one per tier, each asserting the full contract:
  the flip is detected BEFORE any token is emitted from poisoned bytes,
  the chain truncates at the bad suffix, generation recomputes to greedy
  parity with a never-corrupted baseline, and pages return to baseline.
  - host DRAM: rot caught at restore time and by the background scrubber
  - remote store: rot at rest caught at serve time, with the holder's
    ``BadBlock(remote)`` + ``BlockRemoved(remote)`` pair
  - in flight: a corrupted ``BlockPayload`` frame is rejected at import
    (install stops at the bad frame) and at remote-store accept
- **Export truncation**: a corrupt host block is caught while BUILDING
  an export — the response truncates at the bad suffix instead of
  shipping poisoned bytes.
- **Fleet revocation conformance**: a ``BadBlock`` event through the
  events pool drops the holder's index entry on every backend
  (in-memory, cost-aware, redis, instrumented, native) and through
  ``ShardedIndex``/``ShardedEventsPool``; replica purges fan out via
  ``on_bad_block``; routes already in flight attribute as
  ``quarantined``.
- **Knobs-off parity pins**: KV_INTEGRITY off = no digest table, no
  wire digests (encode bytes pinned), legacy /stats keys, no
  ``kvcache_integrity_*`` exposition.
- **Hammer**: concurrent record/check/quarantine over the digest table
  (runs under LOCKTRACE=1 in CI).
"""

import threading

import numpy as np
import pytest

from chaos import corrupt_host_slot, corrupt_payload, corrupt_remote_block
from fake_redis import FakeRedis
from llm_d_kv_cache_manager_tpu.kvcache.integrity import (
    CHECK_CORRUPT,
    CHECK_OK,
    CHECK_UNVERIFIED,
    BlockIntegrity,
    page_digest,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    CostAwareMemoryIndex,
    CostAwareMemoryIndexConfig,
    DeviceTier,
    InMemoryIndex,
    InMemoryIndexConfig,
    InstrumentedIndex,
    Key,
    NativeMemoryIndex,
    NativeMemoryIndexConfig,
    PodEntry,
    RedisIndexConfig,
    native_available,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import RedisIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    EventBatch,
    KVEventsPool,
    KVEventsPoolConfig,
    Message,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents.events import (
    BadBlock,
    decode_event_batch,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvevents.health import (
    FleetHealth,
    FleetHealthConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.transfer import (
    RemoteBlockStore,
    RemoteStoreConfig,
    protocol,
)
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA, quant
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    Engine,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)

PS = 4
MODEL = "tiny-llama"
SHAPE = (TINY_LLAMA.n_layers, PS, TINY_LLAMA.n_kv_heads, TINY_LLAMA.hd)
SCALE_BYTES = int(np.prod(quant.kv_scale_shape(SHAPE))) * 4


def _engine_cfg(total_pages=64, **kw):
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(
            total_pages=total_pages,
            page_size=PS,
            host_pages=kw.pop("host_pages", 0),
        ),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
        **kw,
    )


def _engine(total_pages=64, on_events=None, **kw):
    return Engine(_engine_cfg(total_pages=total_pages, **kw), on_events=on_events)


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _baseline(n=5, tokens=4):
    """Greedy outputs from a never-evicted, never-corrupted engine."""
    base = _engine(total_pages=64)
    want = {}
    for i in range(n):
        seq = base.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=tokens))
        base.run_until_complete()
        want[i] = list(seq.generated_tokens)
    return want


def _store(eng, capacity=256, on_events=None):
    return RemoteBlockStore(
        RemoteStoreConfig(
            capacity_pages=capacity,
            page_size=PS,
            page_shape=SHAPE,
            dtype="float32",
            scale_bytes=SCALE_BYTES,
            init_hash=eng.block_manager.token_db.init_hash,
        ),
        on_events=on_events,
        integrity=eng.integrity,
    )


# -- digest + table units -----------------------------------------------------
class TestPageDigest:
    def test_deterministic_and_order_sensitive(self):
        assert page_digest(b"kk", b"vv") == page_digest(b"kk", b"vv")
        assert page_digest(b"kk", b"vv") != page_digest(b"vv", b"kk")
        assert page_digest(b"kk", b"vv") != page_digest(b"kkv", b"v")

    def test_scales_are_covered(self):
        base = page_digest(b"k", b"v")
        assert page_digest(b"k", b"v", b"s", b"") != base
        assert page_digest(b"k", b"v", b"", b"s") != base
        assert page_digest(b"k", b"v", b"a", b"b") != page_digest(
            b"k", b"v", b"b", b"a"
        )

    def test_fits_u32(self):
        d = page_digest(b"\xff" * 1024, b"\x00" * 1024)
        assert 0 <= d <= 0xFFFFFFFF


class TestBlockIntegrity:
    def test_check_outcomes(self):
        bi = BlockIntegrity()
        d = page_digest(b"k", b"v")
        bi.record(7, d)
        assert bi.check(7, d, "restore") == CHECK_OK
        assert bi.check(8, d, "restore") == CHECK_UNVERIFIED  # no evidence
        assert bi.check(7, d ^ 1, "restore") == CHECK_CORRUPT
        s = bi.stats
        assert (s["checks_ok"], s["checks_unverified"], s["checks_corrupt"]) == (
            1,
            1,
            1,
        )

    def test_carried_digest_none_is_unverified(self):
        bi = BlockIntegrity()
        assert bi.check_carried(1, None, 123, "import") == CHECK_UNVERIFIED
        assert bi.check_carried(1, 123, 123, "import") == CHECK_OK
        assert bi.check_carried(1, 122, 123, "import") == CHECK_CORRUPT

    def test_quarantine_drops_digest_and_marks(self):
        bi = BlockIntegrity()
        bi.record(7, 1)
        bi.quarantine(7, tier="host_dram")
        assert bi.is_quarantined(7)
        assert bi.expected(7) is None
        # Re-recording (a fresh, recomputed copy) clears the flag.
        bi.record(7, 2)
        assert not bi.is_quarantined(7)

    def test_table_cap_evicts_lru(self):
        bi = BlockIntegrity(table_cap=4)
        for h in range(6):
            bi.record(h, h)
        assert len(bi) == 4
        assert bi.expected(0) is None and bi.expected(5) == 5
        assert bi.stats["table_evictions"] == 2

    def test_snapshot_shape(self):
        bi = BlockIntegrity()
        bi.record(1, 1)
        snap = bi.snapshot()
        assert snap["table_entries"] == 1
        assert snap["quarantine_entries"] == 0
        assert "recorded" in snap and "checks_corrupt" in snap


# -- corruption drills --------------------------------------------------------
class TestHostTierDrill:
    def test_restore_detects_quarantines_and_recomputes(self):
        want = _baseline(4)
        eng = _engine(
            total_pages=12,
            host_pages=32,
            host_tier_policy="always",
            kv_integrity=True,
        )
        events = []
        eng.block_manager.on_events = events.extend
        for i in range(4):
            seq = eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
            assert list(seq.generated_tokens) == want[i]
        free_before = eng.block_manager.num_free
        bm = eng.block_manager
        hashes = bm.token_db.prefix_hashes(_prompt(0, 16))
        assert corrupt_host_slot(
            eng, hashes[0]
        ), "chain 0 must be host-resident for the drill"
        # Re-serve prompt 0: the bring-back MUST catch the flip before any
        # token is emitted, quarantine the block, and recompute cold to
        # exact greedy parity.
        seq = eng.add_request(_prompt(0, 16), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert list(seq.generated_tokens) == want[0]
        assert eng.integrity.stats["checks_corrupt"] >= 1
        assert eng.integrity.stats["quarantined"] >= 1
        assert eng.integrity.is_quarantined(hashes[0]) or hashes[0] in bm._host_cached
        bad = [e for e in events if isinstance(e, BadBlock)]
        assert bad and bad[0].medium == "host_dram"
        assert hashes[0] in bad[0].block_hashes
        # Pages back to baseline: nothing leaked across the quarantine.
        eng._flush_page_moves()
        assert eng.block_manager.num_free == free_before

    def test_scrubber_catches_latent_rot(self):
        eng = _engine(
            total_pages=12,
            host_pages=32,
            host_tier_policy="always",
            kv_integrity=True,
        )
        for i in range(4):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        eng._flush_page_moves()
        bm = eng.block_manager
        assert bm._host_cached, "no host-resident pages to scrub"
        victim = next(iter(bm._host_cached))
        slot = bm._host_cached[victim]
        eng._host_k[slot].reshape(-1).view("uint8")[3] ^= 0x80
        checked = eng.scrub_host_pages(64)
        assert checked > 0
        assert eng.integrity.stats["checks_corrupt"] == 1
        assert eng.integrity.stats["scrub_pages"] == checked
        assert victim not in bm._host_cached  # quarantined, not servable
        assert eng.integrity.is_quarantined(victim)

    def test_scrub_clean_tier_is_all_ok(self):
        eng = _engine(
            total_pages=12,
            host_pages=32,
            host_tier_policy="always",
            kv_integrity=True,
        )
        for i in range(3):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        checked = eng.scrub_host_pages(64)
        assert checked > 0
        assert eng.integrity.stats["checks_corrupt"] == 0
        assert eng.integrity.stats["checks_ok"] == checked


class TestRemoteTierDrill:
    def test_serve_detects_rot_revokes_and_recomputes(self):
        want = _baseline(5)
        eng = _engine(total_pages=12, remote_tier=True, kv_integrity=True)
        events = []
        store = _store(eng, on_events=events.extend)
        eng.on_demotion = store.accept
        for i in range(5):
            seq = eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
            assert list(seq.generated_tokens) == want[i]
        # Demoted payloads carry their digests.
        assert len(store) > 0
        assert all(b.digest is not None for b in store._blocks.values())
        hashes = eng.block_manager.token_db.prefix_hashes(_prompt(0, 16))
        assert hashes[0] in store
        assert corrupt_remote_block(store, hashes[0])
        served = store.serve(hashes)
        # The rotted head breaks the run before ANY payload ships.
        assert served == []
        assert store.stats["quarantined"] == 1
        assert hashes[0] not in store
        removed = [e for e in events if type(e).__name__ == "BlockRemoved"]
        bad = [e for e in events if isinstance(e, BadBlock)]
        assert any(hashes[0] in e.block_hashes for e in removed)
        assert bad and bad[0].medium == "remote"
        # Cold recompute: greedy parity with the never-corrupted baseline.
        seq = eng.add_request(_prompt(0, 16), SamplingParams(max_new_tokens=4))
        eng.run_until_complete()
        assert list(seq.generated_tokens) == want[0]

    def test_accept_rejects_corrupt_push(self):
        eng = _engine(total_pages=12, remote_tier=True, kv_integrity=True)
        payloads = []
        eng.on_demotion = payloads.extend
        for i in range(5):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        assert payloads
        store = _store(eng)
        corrupt_payload(payloads, which=0)
        accepted = store.accept(payloads, source_pod="pusher-1")
        assert accepted == len(payloads) - 1
        assert store.stats["digest_rejected"] == 1
        assert payloads[0].block_hash not in store

    def test_purge_drops_revoked_replicas(self):
        eng = _engine(total_pages=12, remote_tier=True, kv_integrity=True)
        events = []
        store = _store(eng, on_events=events.extend)
        eng.on_demotion = store.accept
        for i in range(5):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        victims = list(store._blocks)[:2]
        assert store.purge(victims + [999]) == 2
        assert all(h not in store for h in victims)
        assert store.stats["purged"] == 2
        removed = [e for e in events if type(e).__name__ == "BlockRemoved"]
        assert any(set(victims) <= set(e.block_hashes) for e in removed)


class TestInFlightDrill:
    def test_import_rejects_corrupt_frame_before_install(self):
        want = _baseline(2)
        donor = _engine(total_pages=64, kv_integrity=True)
        donor.add_request(_prompt(1, 16), SamplingParams(max_new_tokens=1))
        donor.run_until_complete()
        hashes = donor.block_manager.token_db.prefix_hashes(_prompt(1, 16))
        blocks = donor.export_kv_blocks(hashes)
        assert blocks and all(b.digest is not None for b in blocks)
        events = []
        recv = _engine(total_pages=64, kv_integrity=True, on_events=events.extend)
        corrupt_payload(blocks, which=1)
        # Installs the clean prefix, stops AT the corrupt frame — the
        # poisoned bytes never reach a page pool.
        assert recv.import_kv_blocks(blocks, source_pod="donor-pod") == 1
        assert recv.transfer_stats["import_rejected"] == 1
        assert recv.integrity.stats["checks_corrupt"] == 1
        bad = [e for e in events if isinstance(e, BadBlock)]
        assert bad and bad[0].pod == "donor-pod"
        assert blocks[1].block_hash in bad[0].block_hashes
        # Greedy parity: the gap recomputes, zero corrupted tokens.
        seq = recv.add_request(_prompt(1, 16), SamplingParams(max_new_tokens=4))
        recv.run_until_complete()
        assert list(seq.generated_tokens) == want[1]

    def test_wire_round_trip_preserves_digest(self):
        donor = _engine(total_pages=64, kv_integrity=True)
        donor.add_request(_prompt(1, 16), SamplingParams(max_new_tokens=1))
        donor.run_until_complete()
        hashes = donor.block_manager.token_db.prefix_hashes(_prompt(1, 16))
        blocks = donor.export_kv_blocks(hashes)
        got = protocol.decode_response(protocol.encode_response(blocks, True))
        assert got is not None
        decoded, _complete, err = got
        assert err is None
        assert [b.digest for b in decoded] == [b.digest for b in blocks]
        assert all(b.digest is not None for b in decoded)


class TestExportTruncation:
    def test_export_truncates_at_corrupt_host_block(self):
        eng = _engine(
            total_pages=12,
            host_pages=32,
            host_tier_policy="always",
            kv_integrity=True,
        )
        for i in range(4):
            eng.add_request(_prompt(i, 16), SamplingParams(max_new_tokens=4))
            eng.run_until_complete()
        eng._flush_page_moves()
        bm = eng.block_manager
        hashes = bm.token_db.prefix_hashes(_prompt(0, 16))
        host_run = [h for h in hashes if h in bm._host_cached]
        assert len(host_run) >= 2, "need a multi-block host run"
        # Corrupt the SECOND host block of the chain: the export must ship
        # the clean prefix and truncate at the bad suffix.
        bad = host_run[1]
        slot = bm._host_cached[bad]
        eng._host_k[slot].reshape(-1).view("uint8")[0] ^= 0xFF
        blocks = eng.export_kv_blocks(hashes)
        assert [b.block_hash for b in blocks] == hashes[: hashes.index(bad)]
        assert eng.integrity.stats["checks_corrupt"] == 1
        assert bad not in bm._host_cached  # quarantined on detection


# -- fleet-wide revocation conformance ---------------------------------------
BACKENDS = {
    "in_memory": lambda: InMemoryIndex(
        InMemoryIndexConfig(size=1000, pod_cache_size=10)
    ),
    "cost_aware": lambda: CostAwareMemoryIndex(
        CostAwareMemoryIndexConfig(max_cost_bytes=10**6)
    ),
    "redis": lambda: RedisIndex(RedisIndexConfig(client=FakeRedis())),
    "instrumented": lambda: InstrumentedIndex(
        InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10))
    ),
}
if native_available():
    BACKENDS["native"] = lambda: NativeMemoryIndex(
        NativeMemoryIndexConfig(size=1000, pod_cache_size=10)
    )


def _bad_payload(hashes, pod="", medium=None):
    return EventBatch(
        ts=0.0, events=[BadBlock(block_hashes=hashes, pod=pod, medium=medium)]
    ).to_payload()


@pytest.fixture(params=list(BACKENDS))
def index(request):
    return BACKENDS[request.param]()


class TestRevocationConformance:
    def _pool(self, index, **kw):
        pool = KVEventsPool(index, KVEventsPoolConfig(concurrency=1), **kw)
        pool.start()
        return pool

    def test_bad_block_revokes_all_tiers(self, index):
        index.add([Key(MODEL, 7)], [PodEntry("pod-1", DeviceTier.TPU_HBM)])
        index.add([Key(MODEL, 7)], [PodEntry("pod-1", DeviceTier.HOST_DRAM)])
        pool = self._pool(index)
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _bad_payload([7])))
            assert pool.drain()
            assert index.lookup([Key(MODEL, 7)], set()).get(Key(MODEL, 7), []) == []
        finally:
            pool.shutdown()

    def test_bad_block_medium_scoped(self, index):
        index.add([Key(MODEL, 7)], [PodEntry("pod-1", DeviceTier.TPU_HBM)])
        index.add([Key(MODEL, 7)], [PodEntry("pod-1", DeviceTier.HOST_DRAM)])
        pool = self._pool(index)
        try:
            pool.add_task(
                Message("t", "pod-1", MODEL, _bad_payload([7], medium="host_dram"))
            )
            assert pool.drain()
            # The HBM entry survives a host_dram-scoped revocation.
            assert index.lookup([Key(MODEL, 7)], set())[Key(MODEL, 7)] == ["pod-1"]
        finally:
            pool.shutdown()

    def test_bad_block_holder_identity(self, index):
        """A detector publishing on a peer's behalf (``ev.pod``) revokes
        the HOLDER's entry, not its own."""
        index.add([Key(MODEL, 7)], [PodEntry("holder-pod", DeviceTier.REMOTE)])
        index.add([Key(MODEL, 7)], [PodEntry("detector-pod", DeviceTier.TPU_HBM)])
        pool = self._pool(index)
        try:
            pool.add_task(
                Message(
                    "t",
                    "detector-pod",
                    MODEL,
                    _bad_payload([7], pod="holder-pod", medium="remote"),
                )
            )
            assert pool.drain()
            assert index.lookup([Key(MODEL, 7)], set())[Key(MODEL, 7)] == [
                "detector-pod"
            ]
        finally:
            pool.shutdown()

    def test_on_bad_block_purge_fans_out(self, index):
        calls = []
        pool = self._pool(
            index, on_bad_block=lambda pod, hs, m: calls.append((pod, hs, m))
        )
        try:
            pool.add_task(
                Message("t", "pod-1", MODEL, _bad_payload([7, 8], medium="remote"))
            )
            assert pool.drain()
            assert calls == [("pod-1", [7, 8], "remote")]
        finally:
            pool.shutdown()

    def test_health_counts_bad_blocks_without_liveness_impact(self, index):
        health = FleetHealth(FleetHealthConfig(pod_ttl_s=0))
        pool = self._pool(index, health=health)
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _bad_payload([1, 2, 3])))
            assert pool.drain()
            assert health.bad_blocks_reported == 3
            # A noisy-but-alive pod stays routable: revocation is about
            # blocks, never liveness.
            assert health.is_routable("pod-1")
        finally:
            pool.shutdown()


class TestShardedRevocation:
    def test_sharded_pool_revokes_across_shards(self):
        from llm_d_kv_cache_manager_tpu.kvcache.sharding import (
            ShardedEventsPool,
            ShardedEventsPoolConfig,
            ShardedIndex,
        )

        sharded = ShardedIndex([InMemoryIndex() for _ in range(3)], vnodes=8)
        hashes = list(range(20))
        sharded.add([Key(MODEL, h) for h in hashes], [PodEntry("pod-1", DeviceTier.TPU_HBM)])
        calls = []
        pool = ShardedEventsPool(
            sharded,
            ShardedEventsPoolConfig(dispatchers=2),
            on_bad_block=lambda pod, hs, m: calls.append((pod, list(hs), m)),
        )
        pool.start()
        try:
            pool.add_task(Message("t", "pod-1", MODEL, _bad_payload(hashes)))
            assert pool.drain()
            got = sharded.lookup([Key(MODEL, h) for h in hashes], set())
            assert all(got.get(Key(MODEL, h), []) == [] for h in hashes)
            assert calls and calls[0][0] == "pod-1"
        finally:
            pool.shutdown()


class TestBadBlockWire:
    def test_round_trip(self):
        ev = decode_event_batch(_bad_payload([1, 2], pod="p", medium="remote")).events[0]
        assert isinstance(ev, BadBlock)
        assert ev.block_hashes == [1, 2]
        assert ev.pod == "p" and ev.medium == "remote"

    def test_minimal_form_trailing_fields_elided(self):
        import msgpack

        payload = _bad_payload([5])
        assert payload == msgpack.packb(
            [0.0, [["BadBlock", [5]]]], use_bin_type=True
        )
        ev = decode_event_batch(payload).events[0]
        assert ev.pod == "" and ev.medium is None


# -- knobs-off parity pins ----------------------------------------------------
class TestKnobsOffParity:
    def test_engine_defaults_off(self):
        eng = _engine(total_pages=12)
        assert EngineConfig.__dataclass_fields__["kv_integrity"].default is False
        assert eng.integrity is None
        assert eng.block_manager._integrity is None
        assert eng.block_manager._host_verify is None

    def test_no_digests_on_wire_when_off(self):
        eng = _engine(total_pages=64)
        eng.add_request(_prompt(2, 16), SamplingParams(max_new_tokens=1))
        eng.run_until_complete()
        hashes = eng.block_manager.token_db.prefix_hashes(_prompt(2, 16))
        blocks = eng.export_kv_blocks(hashes)
        assert blocks and all(b.digest is None for b in blocks)
        # Encoded block rows stay at the legacy arity — not a byte moves.
        import msgpack

        raw = msgpack.unpackb(
            protocol.encode_response(blocks, True), use_list=True
        )
        assert all(len(row) <= 11 for row in raw[2])

    def test_store_stats_keys_pinned_when_off(self):
        eng = _engine(total_pages=12, remote_tier=True)
        store = _store(eng)  # integrity=None rides the engine's None
        assert set(store.stats) == {"accepted", "rejected", "evicted", "served"}

    def test_outputs_identical_knob_on_vs_off(self):
        outs = {}
        for knob in (False, True):
            eng = _engine(
                total_pages=12,
                host_pages=32,
                host_tier_policy="always",
                kv_integrity=knob,
            )
            got = []
            for i in range(4):
                seq = eng.add_request(
                    _prompt(i, 16), SamplingParams(max_new_tokens=4)
                )
                eng.run_until_complete()
                got.append(list(seq.generated_tokens))
            outs[knob] = got
        assert outs[False] == outs[True]

    def test_exposition_gated(self):
        pytest.importorskip("prometheus_client")
        from llm_d_kv_cache_manager_tpu.server.serve import _ServingMetrics

        off = _ServingMetrics(obs=True).exposition().decode()
        assert "kvcache_integrity" not in off
        on = _ServingMetrics(obs=True, integrity=True)
        on.sync_integrity_stats(
            {
                "checks_ok": 2,
                "checks_corrupt": 1,
                "checks_unverified": 0,
                "quarantined": 1,
                "scrub_pages": 8,
            }
        )
        text = on.exposition().decode()
        assert 'kvcache_integrity_checks_total{outcome="ok"} 2.0' in text
        assert 'kvcache_integrity_checks_total{outcome="corrupt"} 1.0' in text
        assert "kvcache_integrity_quarantined_total 1.0" in text
        assert "kvcache_integrity_scrub_pages_total 8.0" in text


# -- concurrency hammer (runs under LOCKTRACE=1 in CI) ------------------------
class TestDigestTableHammer:
    def test_concurrent_record_check_quarantine(self):
        bi = BlockIntegrity(table_cap=256)
        stop = threading.Event()
        errors = []

        def writer(base):
            i = 0
            while not stop.is_set():
                h = base + (i % 512)
                bi.record(h, page_digest(b"k%d" % h, b"v"))
                i += 1

        def checker():
            while not stop.is_set():
                for h in range(0, 512, 7):
                    bi.check(h, page_digest(b"k%d" % h, b"v"), "scrub")

        def reaper():
            while not stop.is_set():
                for h in range(0, 512, 13):
                    bi.quarantine(h, tier="host_dram")
                    bi.is_quarantined(h)
                bi.snapshot()

        threads = [
            threading.Thread(target=writer, args=(0,)),
            threading.Thread(target=writer, args=(256,)),
            threading.Thread(target=checker),
            threading.Thread(target=reaper),
        ]

        def run():
            try:
                for t in threads:
                    t.start()
                stop.wait(0.5)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10)

        try:
            run()
        except Exception as e:  # pragma: no cover - hammer must not raise
            errors.append(e)
        assert not errors
        assert len(bi) <= 256
        snap = bi.snapshot()
        assert snap["recorded"] >= snap["table_entries"]
