"""locktrace: runtime lock-order and guarded-attribute race detection.

The static half (``tools/kvlint``, rule ``lock-discipline``) checks what
is lexically provable; this module catches what only execution reveals:

- **lock-order cycles.** Every instrumented acquire records the set of
  locks the acquiring thread already holds and adds edges
  ``held -> acquired`` to a global lock-order graph. A cycle in that
  graph is a potential deadlock (thread 1 takes A then B, thread 2 takes
  B then A — each waits on the other), flagged the FIRST time the
  inverted order is exercised, long before the interleaving that would
  actually deadlock. This is the classic happens-before order check that
  gives Go's ``-race`` and pthread lockdep their payoff.
- **unguarded cross-thread mutation.** ``guard_attrs(obj, lock, *attrs)``
  rebinds the object's class so every write (and optionally read) of a
  guarded attribute asserts the lock is held by the writing thread —
  the runtime twin of the ``# guarded_by:`` annotation.

Opt-in and test-only by design: ``activate()`` monkeypatches
``threading.Lock``/``threading.RLock`` factories so EVERY lock created
afterwards is traced; tests enable it via the ``LOCKTRACE=1`` env var
(see ``tests/conftest.py``, wired into the concurrency hammer and chaos
suites). Zero cost when not activated — production code paths never
import anything from here.
"""

from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = [
    "activate",
    "deactivate",
    "enabled",
    "reset",
    "violations",
    "assert_clean",
    "TracingLock",
    "guard_attrs",
    "Violation",
]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def enabled() -> bool:
    """True when the harness is requested for this process (``LOCKTRACE=1``)."""
    return os.environ.get("LOCKTRACE", "").strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class Violation:
    kind: str  # "lock-order-cycle" | "unguarded-mutation"
    message: str
    stack: str

    def render(self) -> str:
        return f"[{self.kind}] {self.message}\n{self.stack}"


@dataclass
class _Graph:
    """Global lock-order graph + held-lock bookkeeping, single mutex."""

    mu: threading.Lock = field(default_factory=_REAL_LOCK)
    #: lock name -> set of lock names acquired while it was held
    edges: dict[str, set[str]] = field(default_factory=dict)
    #: (held, acquired) -> acquisition stack that created the edge
    edge_sites: dict[tuple[str, str], str] = field(default_factory=dict)
    violations: list[Violation] = field(default_factory=list)
    #: edges already reported, so a hot inverted pair fires once
    reported: set[tuple[str, str]] = field(default_factory=set)


_GRAPH = _Graph()
#: per-thread list of (lock name, lock instance id) in acquisition order.
#: Order-graph edges use the NAME (allocation-site "lock class", lockdep
#: granularity); ownership checks (guard_attrs, Condition._is_owned) use
#: the instance id so two same-site locks never alias each other's holds.
_HELD = threading.local()


def _held_stack() -> list[tuple[str, int]]:
    stack = getattr(_HELD, "entries", None)
    if stack is None:
        stack = []
        _HELD.entries = stack
    return stack


def _find_cycle(start: str, target: str) -> Optional[list[str]]:
    """Path target ->* start in the edge graph (so start -> target closes
    a cycle). Iterative DFS; the graph is tiny (locks in one process)."""
    path = [target]
    seen = {target}
    stack: list[tuple[str, Iterable[str]]] = [
        (target, iter(_GRAPH.edges.get(target, ())))
    ]
    while stack:
        node, it = stack[-1]
        found = None
        for nxt in it:
            if nxt == start:
                return path + [start]
            if nxt not in seen:
                found = nxt
                break
        if found is None:
            stack.pop()
            path.pop()
            continue
        seen.add(found)
        path.append(found)
        stack.append((found, iter(_GRAPH.edges.get(found, ()))))
    return None


def _record_acquire(name: str, lock_id: int, reentrant: bool) -> None:
    held = _held_stack()
    if not held:
        held.append((name, lock_id))
        return
    stack_txt: Optional[str] = None  # formatted lazily: hot path
    with _GRAPH.mu:
        for h, _hid in held:
            if h == name and reentrant:
                # Same lock class re-acquired by an RLock: legal
                # re-entrance. (Lock identity is the allocation site, so
                # cross-instance nesting within one class is conflated
                # with it — the lockdep granularity tradeoff.) A
                # NON-reentrant Lock nesting its own class is kept: same
                # instance would self-deadlock, two instances are an
                # unordered pair — both worth a violation.
                continue
            edge = (h, name)
            _GRAPH.edges.setdefault(h, set()).add(name)
            if edge not in _GRAPH.edge_sites:
                if stack_txt is None:
                    stack_txt = "".join(traceback.format_stack(limit=12)[:-2])
                _GRAPH.edge_sites[edge] = stack_txt
            cycle = _find_cycle(h, name)
            if cycle is not None and edge not in _GRAPH.reported:
                if stack_txt is None:
                    stack_txt = "".join(traceback.format_stack(limit=12)[:-2])
                _GRAPH.reported.add(edge)
                back_site = _GRAPH.edge_sites.get(
                    (cycle[0], cycle[1]), "(edge site unknown)"
                )
                _GRAPH.violations.append(
                    Violation(
                        kind="lock-order-cycle",
                        message=(
                            "lock acquisition order inverted: "
                            + " -> ".join(cycle + [cycle[0]])
                            + f" (this thread holds {h!r} and is taking "
                            f"{name!r}; another code path takes them in the "
                            "opposite order — potential ABBA deadlock)"
                        ),
                        stack=(
                            "forward acquisition:\n"
                            + stack_txt
                            + "conflicting prior edge recorded at:\n"
                            + back_site
                        ),
                    )
                )
    held.append((name, lock_id))


def _record_release(name: str, lock_id: int) -> None:
    held = _held_stack()
    # release order need not be LIFO; drop the most recent matching entry
    for i in range(len(held) - 1, -1, -1):
        if held[i] == (name, lock_id):
            del held[i]
            return


class TracingLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper feeding the order graph.

    Named by allocation site (``file:line``) so violations point at the
    lock's birthplace, the stable identity a human can act on.
    """

    def __init__(self, reentrant: bool = False, name: Optional[str] = None):
        self._lock = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self._reentrant = reentrant
        if name is None:
            # allocation site: nearest frame outside this module
            for fr in reversed(traceback.extract_stack(limit=8)[:-1]):
                if "locktrace" not in fr.filename:
                    name = f"{os.path.basename(fr.filename)}:{fr.lineno}"
                    break
        self.name = name or "lock:?"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            _record_acquire(self.name, id(self), self._reentrant)
        return got

    def release(self) -> None:
        self._lock.release()
        _record_release(self.name, id(self))

    def __enter__(self) -> "TracingLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") else False

    def held_by_current_thread(self) -> bool:
        """THIS instance (not merely its lock class) held by the caller."""
        return (self.name, id(self)) in _held_stack()

    # condition variables etc. reach for the raw lock's protocol
    def _is_owned(self):  # pragma: no cover - RLock/Condition internals
        if hasattr(self._lock, "_is_owned"):
            return self._lock._is_owned()
        return self.held_by_current_thread()

    def __getattr__(self, name: str):
        # CPython internals (``_at_fork_reinit``, ``_release_save``,
        # ``_acquire_restore``) and any future lock protocol surface
        # delegate to the real lock — only attributes not defined above
        # reach here.
        return getattr(self._lock, name)


def activate() -> None:
    """Route ``threading.Lock``/``RLock`` creation through TracingLock.

    Locks created BEFORE activation stay raw (interpreter internals,
    import-time singletons) — the fleet under test creates its locks at
    object construction, inside the activated window.
    """
    threading.Lock = lambda: TracingLock(reentrant=False)  # type: ignore[misc]
    threading.RLock = lambda: TracingLock(reentrant=True)  # type: ignore[misc]


def deactivate() -> None:
    threading.Lock = _REAL_LOCK  # type: ignore[misc]
    threading.RLock = _REAL_RLOCK  # type: ignore[misc]


def reset() -> None:
    """Clear the order graph and violations (between tests)."""
    with _GRAPH.mu:
        _GRAPH.edges.clear()
        _GRAPH.edge_sites.clear()
        _GRAPH.violations.clear()
        _GRAPH.reported.clear()


def violations() -> list[Violation]:
    with _GRAPH.mu:
        return list(_GRAPH.violations)


def assert_clean() -> None:
    """Raise AssertionError listing every recorded violation (test gate)."""
    vs = violations()
    if vs:
        raise AssertionError(
            f"locktrace recorded {len(vs)} violation(s):\n\n"
            + "\n\n".join(v.render() for v in vs)
        )


def _record_unguarded(obj: object, attr: str, lock: object) -> None:
    with _GRAPH.mu:
        _GRAPH.violations.append(
            Violation(
                kind="unguarded-mutation",
                message=(
                    f"{type(obj).__name__}.{attr} written by "
                    f"{threading.current_thread().name} without holding its "
                    f"guarding lock ({getattr(lock, 'name', lock)!r}) — the "
                    "guarded_by contract is violated at runtime"
                ),
                stack="".join(traceback.format_stack(limit=10)[:-2]),
            )
        )


def guard_attrs(obj: object, lock: object, *attrs: str) -> object:
    """Runtime twin of ``# guarded_by:``: every subsequent write to the
    named attributes must happen with ``lock`` held by the writing thread.

    Implemented by grafting a one-off subclass with a checking
    ``__setattr__`` onto the instance — no cost to other instances, no
    cost at all when locktrace is off (callers gate on ``enabled()``).
    ``lock`` may be a ``TracingLock`` (precise per-thread ownership) or a
    raw lock (falls back to ``locked()``, a weaker check).
    """
    guarded = frozenset(attrs)
    cls = type(obj)

    def _holds() -> bool:
        if isinstance(lock, TracingLock):
            return lock.held_by_current_thread()
        locked = getattr(lock, "locked", None)
        return bool(locked()) if callable(locked) else True

    def __setattr__(self, name, value):  # noqa: N807
        if name in guarded and not _holds():
            _record_unguarded(self, name, lock)
        super(traced_cls, self).__setattr__(name, value)

    traced_cls = type(
        f"LockTraced{cls.__name__}", (cls,), {"__setattr__": __setattr__}
    )
    obj.__class__ = traced_cls
    return obj
