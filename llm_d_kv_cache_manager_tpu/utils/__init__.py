from .logging import get_logger, DEBUG, TRACE

__all__ = ["get_logger", "DEBUG", "TRACE"]
