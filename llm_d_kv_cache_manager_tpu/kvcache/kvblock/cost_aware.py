"""Cost-aware in-memory index: bounded by estimated byte footprint.

Parity with reference ``pkg/kvcache/kvblock/cost_aware_memory.go``: instead
of bounding by entry *count*, each key's entry is charged an estimated byte
cost (strings + per-entry overhead, mirroring ``CalculateByteSize``
``cost_aware_memory.go:111-143``) and the store evicts least-recently-used
keys until the total cost fits the configured budget (default 2 GiB).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence

from ...utils import get_logger
from .index import CostAwareMemoryIndexConfig, Index
from .keys import Key, PodEntry

log = get_logger("kvcache.kvblock.cost_aware")

# Fixed bookkeeping overhead charged per key entry and per pod entry, on top
# of string payloads. Deliberately generous: the goal is an upper-bound-ish
# estimate so the budget is honored, not exact accounting.
_KEY_OVERHEAD = 96
_POD_OVERHEAD = 64


def estimate_entry_cost(key: Key, pods: set[PodEntry]) -> int:
    cost = _KEY_OVERHEAD + len(key.model_name) + 8  # model string + uint64 hash
    for p in pods:
        cost += _POD_OVERHEAD + len(p.pod_identifier) + len(str(p.device_tier))
    return cost


class CostAwareMemoryIndex(Index):
    def __init__(self, config: Optional[CostAwareMemoryIndexConfig] = None):
        self.config = config or CostAwareMemoryIndexConfig()
        if self.config.max_cost_bytes < 1:
            raise ValueError("max_cost_bytes must be >= 1")
        self._lock = threading.RLock()
        self._data: OrderedDict[Key, set[PodEntry]] = OrderedDict()  # guarded_by: _lock
        self._costs: dict[Key, int] = {}  # guarded_by: _lock
        self._total_cost = 0  # guarded_by: _lock

    @property
    def total_cost(self) -> int:
        with self._lock:
            return self._total_cost

    def _recost(self, key: Key) -> None:  # kvlint: holds=_lock
        """Recompute a key's charge and evict LRU keys while over budget."""
        new_cost = estimate_entry_cost(key, self._data[key])
        self._total_cost += new_cost - self._costs.get(key, 0)
        self._costs[key] = new_cost
        while self._total_cost > self.config.max_cost_bytes and self._data:
            evict_key, _ = self._data.popitem(last=False)
            self._total_cost -= self._costs.pop(evict_key, 0)
            log.trace("cost eviction", key=str(evict_key))

    def lookup(
        self, keys: Sequence[Key], pod_filter: Optional[set[str]] = None
    ) -> dict[Key, list[str]]:
        if not keys:
            raise ValueError("no keys provided for lookup")
        pods_per_key: dict[Key, list[str]] = {}
        with self._lock:
            for key in keys:
                pods = self._data.get(key)
                if pods is None:
                    continue
                self._data.move_to_end(key)
                if not pods:
                    return pods_per_key
                if not pod_filter:
                    pods_per_key[key] = [e.pod_identifier for e in pods]
                else:
                    filtered = [
                        e.pod_identifier for e in pods if e.pod_identifier in pod_filter
                    ]
                    if filtered:
                        pods_per_key[key] = filtered
        return pods_per_key

    def add(self, keys: Sequence[Key], entries: Sequence[PodEntry]) -> None:
        if not keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        with self._lock:
            for key in keys:
                pods = self._data.get(key)
                if pods is None:
                    pods = set()
                    self._data[key] = pods
                else:
                    self._data.move_to_end(key)
                pods.update(entries)
                self._recost(key)

    def evict(self, key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        with self._lock:
            pods = self._data.get(key)
            if pods is None:
                return
            for entry in entries:
                pods.discard(entry)
            if not pods:
                del self._data[key]
                self._total_cost -= self._costs.pop(key, 0)
            else:
                self._recost(key)

    def size_info(self) -> dict:
        with self._lock:
            pods = {e.pod_identifier for ps in self._data.values() for e in ps}
            return {"blocks": len(self._data), "pods": len(pods)}

    def pod_names(self) -> list[str]:
        with self._lock:
            return sorted(
                {e.pod_identifier for ps in self._data.values() for e in ps}
            )

    def evict_pod(self, pod_identifier: str) -> int:
        removed = 0
        with self._lock:
            for key in list(self._data):
                pods = self._data[key]
                stale = [e for e in pods if e.pod_identifier == pod_identifier]
                if not stale:
                    continue
                pods.difference_update(stale)
                removed += len(stale)
                if not pods:
                    del self._data[key]
                    self._total_cost -= self._costs.pop(key, 0)
                else:
                    self._recost(key)
        if removed:
            log.debug("swept pod from index", pod=pod_identifier, entries=removed)
        return removed
