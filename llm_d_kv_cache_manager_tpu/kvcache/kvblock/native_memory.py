"""Native (C++) in-memory index backend.

Same contract and two-level-LRU semantics as ``InMemoryIndex`` (the parity
port of the reference's ``in_memory.go``), with the hot structure in C++
behind a ctypes boundary: integer-only calls on the lookup path (model and
pod names are interned to u32 ids here, tiers to u8), one native call per
``lookup``/``add`` batch instead of per-key Python dict/lock traffic.

Read paths take NO Python lock: the intern tables are copy-on-write — every
mutation (interning a new pod/model under ``_mu``, a rare event at fleet
scale) publishes a fresh immutable snapshot in a single attribute store
(atomic under the GIL), and readers resolve names through whatever snapshot
they grabbed. A reader racing an intern either sees the name (and resolves
it) or doesn't (and treats it as never-seen — exactly what the pre-publish
state was). ``lookup_hashes_ro`` additionally uses the C++ shared-lock
read-side walk (no LRU promotion), so sharded score fan-outs proceed
concurrently with event applies end to end.

Passes the same backend conformance suite as every other Index
(tests/test_index_backends.py), and is selected via
``IndexConfig.native_memory`` when the shared library is built.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from ...native import lruindex as _native
from ...utils import get_logger
from .index import Index, NativeMemoryIndexConfig
from .keys import DeviceTier, Key, PodEntry

log = get_logger("kvcache.kvblock.native_memory")

_TIERS = list(DeviceTier)
_TIER_TO_ID = {t: i for i, t in enumerate(_TIERS)}


def native_available() -> bool:
    return _native.available()


class _Interns:
    """One immutable published generation of the intern tables. Instances
    are never mutated after construction — ``InternStore`` replaces the
    whole snapshot under its write lock, readers dereference lock-free."""

    __slots__ = ("model_ids", "pod_ids", "pod_names")

    def __init__(self, model_ids: dict, pod_ids: dict, pod_names: tuple):
        self.model_ids = model_ids
        self.pod_ids = pod_ids
        self.pod_names = pod_names


class InternStore:
    """Pod/model name ↔ u32 id tables. One per index by default; a shard
    GROUP (``NativeMemoryIndex.shard_group``) shares one so ids are
    comparable across every shard's C structure — the cross-shard fused
    scorer intersects pod ids from different shards in one C call, which
    is only meaningful under a common interning. Write side under ``_mu``
    (interning is once per new name ever seen); readers use the
    atomically published immutable ``snap``."""

    def __init__(self):
        self._mu = threading.Lock()
        self._model_ids: dict[str, int] = {}  # guarded_by: _mu
        self._pod_ids: dict[str, int] = {}  # guarded_by: _mu
        self._pod_names: list[str] = []  # guarded_by: _mu
        #: immutable snapshot, atomically re-published on intern (GIL store)
        self.snap = _Interns({}, {}, ())

    def model_id(self, name: str, *, create: bool) -> Optional[int]:
        mid = self.snap.model_ids.get(name)
        if mid is not None or not create:
            return mid
        with self._mu:
            mid = self._model_ids.get(name)
            if mid is None:
                mid = len(self._model_ids)
                self._model_ids[name] = mid
                self._publish()
            return mid

    def pod_id(self, name: str, *, create: bool) -> Optional[int]:
        pid = self.snap.pod_ids.get(name)
        if pid is not None or not create:
            return pid
        with self._mu:
            pid = self._pod_ids.get(name)
            if pid is None:
                pid = len(self._pod_names)
                self._pod_ids[name] = pid
                self._pod_names.append(name)
                self._publish()
            return pid

    def _publish(self) -> None:  # kvlint: holds=_mu
        self.snap = _Interns(
            dict(self._model_ids), dict(self._pod_ids), tuple(self._pod_names)
        )


class NativeMemoryIndex(Index):
    #: filter id that matches no interned pod: filters everything out while
    #: still walking (and LRU-promoting) the chain like the Python backend.
    _NO_MATCH_FILTER = 0xFFFFFFFF

    def __init__(
        self,
        config: Optional[NativeMemoryIndexConfig] = None,
        *,
        interns: Optional[InternStore] = None,
    ):
        self.config = config or NativeMemoryIndexConfig()
        self._idx = _native.NativeLru(self.config.size, self.config.pod_cache_size)
        #: per-index by default; a shard group passes one shared store
        self._interns = interns if interns is not None else InternStore()

    @classmethod
    def shard_group(
        cls, n_shards: int, config: Optional[NativeMemoryIndexConfig] = None
    ) -> list["NativeMemoryIndex"]:
        """N sub-indexes sharing ONE intern table — the configuration the
        cross-shard fused C scorer requires (``ShardedIndex`` detects it
        and serves score fan-outs in a single native call)."""
        store = InternStore()
        return [cls(config, interns=store) for _ in range(n_shards)]

    # -- interning ----------------------------------------------------------
    @property
    def _snap(self) -> _Interns:
        return self._interns.snap

    def _model_id(self, name: str, *, create: bool) -> Optional[int]:
        return self._interns.model_id(name, create=create)

    def _pod_id(self, name: str, *, create: bool) -> Optional[int]:
        return self._interns.pod_id(name, create=create)

    def _filter_ids(self, pod_filter: Optional[set[str]]) -> list[int]:
        if not pod_filter:
            return []
        pod_ids = self._snap.pod_ids
        ids = [pid for pid in (pod_ids.get(n) for n in pod_filter) if pid is not None]
        # Every filter pod unknown: nothing can match, but the chain must
        # still be walked (and keys promoted) exactly as the Python backend
        # does — a no-match sentinel keeps filtering active.
        return ids or [self._NO_MATCH_FILTER]

    # -- Index contract -----------------------------------------------------
    def lookup(
        self, keys: Sequence[Key], pod_filter: Optional[set[str]] = None
    ) -> dict[Key, list[str]]:
        if not keys:
            raise ValueError("no keys provided for lookup")
        filter_ids = self._filter_ids(pod_filter)
        out: dict[Key, list[str]] = {}
        # One native call per consecutive same-model run (the hot path is
        # always single-model; this keeps mixed-model batches correct).
        i, n = 0, len(keys)
        while i < n:
            j = i
            model = keys[i].model_name
            while j < n and keys[j].model_name == model:
                j += 1
            mid = self._model_id(model, create=False)
            if mid is None:
                i = j  # unknown model: every key missing — chain continues
                continue
            processed, per_key = self._idx.lookup(
                mid, [k.chunk_hash for k in keys[i:j]], filter_ids
            )
            names = self._snap.pod_names
            for key, pods in zip(keys[i:j], per_key):
                if pods:
                    out[key] = [names[pid] for pid, _tier in pods]
            if processed < j - i:  # present-but-empty key: stop the scan
                return out
            i = j
        return out

    def lookup_hashes_ro(
        self,
        model_name: str,
        hashes: Sequence[int],
        pod_filter: Optional[set[str]] = None,
    ) -> Optional[tuple[int, list[list[str]]]]:
        """Read-side lookup from raw chain hashes: C++ shared lock, no LRU
        promotion, no Python lock — the sharded score fan-out's per-shard
        read. Returns ``(processed, per-hash pod-name lists)`` with the
        same early-stop semantics as ``lookup`` (``processed < len(hashes)``
        marks a present-but-empty key at that position), or ``None`` when
        the loaded library predates the read-side symbol (caller falls back
        to the promoting path)."""
        if not self._idx.has_lookup_ro:
            return None
        if not hashes:
            return 0, []
        mid = self._model_id(model_name, create=False)
        if mid is None:
            return len(hashes), [[] for _ in hashes]
        processed, per_key = self._idx.lookup_ro(
            mid, list(hashes), self._filter_ids(pod_filter)
        )
        names = self._snap.pod_names
        return processed, [
            [names[pid] for pid, _tier in pods] for pods in per_key
        ]

    def add_hashes(
        self,
        model_name: str,
        hashes: Sequence[int],
        entries: Sequence[PodEntry],
    ) -> None:
        """Key-free write path from raw chain hashes: one intern pass and
        one native call for the whole run. The sharded event plane's apply
        workers use this so a store burst costs no ``Key`` allocations."""
        if not hashes or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        pods, tiers = [], []
        for e in entries:
            pods.append(self._pod_id(e.pod_identifier, create=True))
            tiers.append(_TIER_TO_ID[e.device_tier])
        mid = self._model_id(model_name, create=True)
        self._idx.add(mid, list(hashes), pods, tiers)

    def add(self, keys: Sequence[Key], entries: Sequence[PodEntry]) -> None:
        if not keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        pods, tiers = [], []
        for e in entries:
            pods.append(self._pod_id(e.pod_identifier, create=True))
            tiers.append(_TIER_TO_ID[e.device_tier])
        i, n = 0, len(keys)
        while i < n:  # one native call per consecutive same-model run
            j = i
            model = keys[i].model_name
            while j < n and keys[j].model_name == model:
                j += 1
            mid = self._model_id(model, create=True)
            self._idx.add(mid, [k.chunk_hash for k in keys[i:j]], pods, tiers)
            i = j

    def evict(self, key: Key, entries: Sequence[PodEntry]) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        mid = self._model_id(key.model_name, create=False)
        if mid is None:
            return
        pod_ids = self._snap.pod_ids
        pods, tiers = [], []
        for e in entries:
            pid = pod_ids.get(e.pod_identifier)
            if pid is None:
                continue
            pods.append(pid)
            tiers.append(_TIER_TO_ID[e.device_tier])
        if pods:
            self._idx.evict(mid, key.chunk_hash, pods, tiers)

    def _distinct_pod_ids(self) -> Optional[list[int]]:
        """Exact distinct pod ids holding >= 1 entry via the C occupancy
        walk; None on a pre-PR-11 library. Exactness matters once shards
        share an intern table: the ever-interned count is GROUP-wide, so
        per-shard gauges fed from it would read identically flat."""
        snap = self._snap
        return self._idx.distinct_pods(max(len(snap.pod_names), 1))

    def size_info(self) -> dict:
        ids = self._distinct_pod_ids()
        if ids is None:
            # Library predates the occupancy walk: pods ever interned this
            # process (a documented superset — see docs/observability.md).
            return {
                "blocks": int(self._idx.size()),
                "pods": len(self._snap.pod_names),
            }
        return {"blocks": int(self._idx.size()), "pods": len(ids)}

    def pod_names(self) -> Optional[Sequence[str]]:
        """Distinct pods currently holding >= 1 entry (exact via the C
        occupancy walk; falls back to the ever-interned superset on an old
        library). Lets the sharded facade union pods across shards."""
        ids = self._distinct_pod_ids()
        names = self._snap.pod_names
        if ids is None:
            return names
        return sorted(names[pid] for pid in ids if pid < len(names))

    def evict_pod(self, pod_identifier: str) -> int:
        pid = self._pod_id(pod_identifier, create=False)
        if pid is None:  # never interned = never added: nothing to sweep
            return 0
        removed = int(self._idx.evict_pod(pid))
        if removed:
            log.debug("swept pod from index", pod=pod_identifier, entries=removed)
        return removed

    def score_longest_prefix(
        self,
        keys: Sequence[Key],
        pod_filter: Optional[set[str]] = None,
    ) -> Optional[dict[str, int]]:
        """Fused lookup+score in one native call (LongestPrefixScorer
        semantics). Returns None when keys span models — the caller then
        falls back to the two-step path."""
        out = self.score_longest_prefix_with_hits(keys, pod_filter)
        return None if out is None else out[0]

    def score_longest_prefix_with_hits(
        self,
        keys: Sequence[Key],
        pod_filter: Optional[set[str]] = None,
    ) -> Optional[tuple[dict[str, int], int]]:
        if not keys:
            return {}, 0
        model = keys[0].model_name
        if any(k.model_name != model for k in keys[1:]):
            return None
        return self.score_hashes_with_hits(
            model, [k.chunk_hash for k in keys], pod_filter
        )

    def score_hashes(
        self,
        model_name: str,
        hashes: Sequence[int],
        pod_filter: Optional[set[str]] = None,
    ) -> dict[str, int]:
        """Fused scoring from raw chain hashes — the zero-object hot path
        (no Key allocation between the hash kernel and the index)."""
        scores, _hits = self.score_hashes_with_hits(model_name, hashes, pod_filter)
        return scores

    def score_hashes_with_hits(
        self,
        model_name: str,
        hashes: Sequence[int],
        pod_filter: Optional[set[str]] = None,
    ) -> tuple[dict[str, int], int]:
        """Like ``score_hashes`` but also returns the lookup-hit count (keys
        with a filter-surviving pod) so the instrumented decorator can report
        metrics identical to the two-step path."""
        if not hashes:
            return {}, 0
        mid = self._model_id(model_name, create=False)
        if mid is None:
            return {}, 0
        scored, hits = self._idx.score(
            mid, hashes, self._filter_ids(pod_filter)
        )
        names = self._snap.pod_names
        return {names[pid]: int(s) for pid, s in scored}, hits

    def __len__(self) -> int:
        return self._idx.size()
