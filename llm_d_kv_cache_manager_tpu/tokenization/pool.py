"""Tokenization worker pool with sync and fire-and-forget modes.

Parity with reference ``pkg/tokenization/pool.go``: N workers (default 5)
consume a queue of (prompt, model) tasks; each task first consults the
prefix store and only runs the full tokenizer when the cached overlap ratio
is below the threshold (default 0.8, ``pool.go:161-191``), writing fresh
tokenizations back to the store. ``tokenize`` blocks for the result;
``enqueue_tokenization`` is fire-and-forget. Failed tasks are retried with
exponential backoff, mirroring the rate-limited workqueue (``:150-155``).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils import get_logger
from .prefixstore import Indexer, LRUTokenStore
from .tokenizer import CachedHFTokenizer, HFTokenizerConfig, Tokenizer

log = get_logger("tokenization.pool")

DEFAULT_WORKERS = 5
DEFAULT_MIN_PREFIX_OVERLAP_RATIO = 0.8
_MAX_RETRIES = 5
_BASE_RETRY_DELAY = 0.005  # 5ms, doubling per attempt (workqueue default style)


@dataclass
class TokenizationPoolConfig:
    workers_count: int = DEFAULT_WORKERS
    min_prefix_overlap_ratio: float = DEFAULT_MIN_PREFIX_OVERLAP_RATIO
    hf_tokenizer: HFTokenizerConfig = field(default_factory=HFTokenizerConfig)


@dataclass
class _Task:
    prompt: str
    model_name: str
    result: Optional["_Future"] = None
    attempts: int = 0


class TokenizationError(RuntimeError):
    """Raised to sync callers when a tokenization task permanently fails."""


class _Future:
    """Single-assignment result slot (the reference's result channel)."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def set(self, value) -> None:
        self._value = value
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def get(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("tokenization timed out")
        if self._error is not None:
            raise self._error
        return self._value


class TokenizationPool:
    def __init__(
        self,
        config: Optional[TokenizationPoolConfig] = None,
        store: Optional[Indexer] = None,
        tokenizer: Optional[Tokenizer] = None,
    ):
        self.config = config or TokenizationPoolConfig()
        self.indexer = store if store is not None else LRUTokenStore()
        self.tokenizer = tokenizer if tokenizer is not None else CachedHFTokenizer(
            self.config.hf_tokenizer
        )
        self._queue: "queue.Queue[Optional[_Task]]" = queue.Queue()
        self._mu = threading.Lock()
        self._threads: list[threading.Thread] = []  # guarded_by: _mu
        self._running = False  # guarded_by: _mu

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        """Start the worker threads (idempotent, non-blocking)."""
        with self._mu:
            if self._running:
                return
            self._running = True
            for i in range(self.config.workers_count):
                t = threading.Thread(
                    target=self._worker_loop, name=f"tokenize-worker-{i}", daemon=True
                )
                t.start()
                self._threads.append(t)

    def shutdown(self) -> None:
        with self._mu:
            if not self._running:
                return
            self._running = False
            for _ in self._threads:
                self._queue.put(None)  # poison pill per worker
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5)

    # -- API ----------------------------------------------------------------
    def enqueue_tokenization(self, prompt: str, model_name: str) -> None:
        """Fire-and-forget (reference ``EnqueueTokenization``)."""
        self._queue.put(_Task(prompt, model_name))

    def tokenize(self, prompt: str, model_name: str, timeout: Optional[float] = 60.0) -> list[int]:
        """Queue a task and block until tokens are available
        (reference ``Tokenize``)."""
        fut = _Future()
        self._queue.put(_Task(prompt, model_name, result=fut))
        return fut.get(timeout)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued task has been processed (for tests and
        the async-throughput benchmark). A task awaiting its retry backoff
        counts as done for this check. Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.002)
        return False

    # -- workers ------------------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            task = self._queue.get()
            try:
                if task is None:
                    return
                self._process_task(task)
            except Exception as exc:
                task.attempts += 1
                if task.attempts >= _MAX_RETRIES:
                    log.error(
                        "tokenization task dropped after retries",
                        model=task.model_name,
                        error=repr(exc),
                    )
                    if task.result is not None:
                        task.result.set_error(
                            TokenizationError(
                                f"tokenization failed for model {task.model_name!r} "
                                f"after {task.attempts} attempts: {exc!r}"
                            )
                        )
                else:
                    delay = _BASE_RETRY_DELAY * (2 ** (task.attempts - 1))
                    threading.Timer(delay, self._requeue, args=(task,)).start()
            finally:
                self._queue.task_done()

    def _requeue(self, task: _Task) -> None:
        """Retry hop; fails the task fast if the pool shut down meanwhile so
        sync callers aren't stranded on a dead queue."""
        with self._mu:
            running = self._running
        if running:
            self._queue.put(task)
        elif task.result is not None:
            task.result.set_error(
                TokenizationError("tokenization pool shut down during retry")
            )

    def _process_task(self, task: _Task) -> None:
        token_ids, overlap_ratio = self.indexer.find_longest_contained_tokens(
            task.prompt, task.model_name
        )

        if overlap_ratio < self.config.min_prefix_overlap_ratio:
            tokens, offsets = self.tokenizer.encode(task.prompt, task.model_name)
            self.indexer.add_tokenization(task.model_name, task.prompt, tokens, offsets)
            token_ids = tokens

        if task.result is not None:
            task.result.set(list(token_ids))
