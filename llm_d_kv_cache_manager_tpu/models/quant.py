"""Weight-only int8 quantization for serving.

Why: a bf16 8B-parameter checkpoint is ~16 GB — the whole HBM of a v5e
chip, leaving nothing for the KV page pool. Symmetric per-output-channel
int8 halves weight bytes (8B fits with room for KV) and halves the HBM
weight traffic that dominates decode, where every matmul is
memory-bound. XLA fuses the dequant (convert + broadcast multiply) into
the dot's operand read on TPU, so no full-size bf16 copy of a weight is
ever resident.

Scheme: for every matmul weight laid out ``[..., in, out]`` (all of this
model family's weights — see ``llama.init_params``), the scale is the
per-output-channel symmetric max over the contraction axis::

    scale = max(|w|, axis=-2, keepdims=True) / 127     # [..., 1, out]
    q     = round(w / scale)  in int8

Dequant is exact in the scale and bounded by scale/2 per element. Norms,
biases, and the MoE router (tiny, and routing decisions are precision
sensitive) stay in the model dtype; the embedding is quantizable but off
by default (gather + lm-head sharing makes its error budget tighter).

No reference counterpart: the reference (llm-d-kv-cache-manager)
delegates model execution to vLLM; this is part of the in-tree TPU
serving stack.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

#: Parameter names eligible for quantization (matmul weights only).
QUANTIZABLE = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedTensor:
    """An int8 weight + its per-output-channel f32 scale, as one pytree
    node so quantized params flow through jit/device_put/checkpointing
    like any other leaf pair."""

    q: Any  # int8, original weight shape [..., in, out]
    scale: Any  # f32, [..., 1, out]

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_tensor(w: jnp.ndarray) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization over axis -2."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def materialize(p: Any, dtype: Any) -> jnp.ndarray:
    """Dequantize (or pass through) a weight for use in a matmul.

    Inside jit this is convert+multiply, which XLA fuses into the
    consuming dot's operand stream — int8 bytes are what cross HBM.
    """
    if isinstance(p, QuantizedTensor):
        return p.q.astype(dtype) * p.scale.astype(dtype)
    return p


def quantize_params(
    params: Any, *, quantize_embed: bool = False, quantize_experts: bool = False
) -> Any:
    """Return the param tree with every eligible matmul weight replaced
    by a :class:`QuantizedTensor`. Leaves everything else untouched.

    MoE expert stacks (3-D ``[E, in, out]`` weights) are SKIPPED by
    default (conservative — expert numerics are routing-sensitive). With
    ``quantize_experts=True`` they run through the Pallas grouped-matmul
    kernel's in-VMEM dequant at ≈ bf16 speed while halving expert HBM
    (round 4; benchmarking/results/moe_dispatch.md — the round-3 2.5×
    ragged_dot penalty no longer applies when ``moe_gmm`` selects the
    kernel, which is the TPU default).
    """

    def convert(d: dict) -> dict:
        out = {}
        for name, v in d.items():
            if name == "layers":
                out[name] = [convert(layer) for layer in v]
            elif name in QUANTIZABLE and (
                getattr(v, "ndim", 2) == 2 or quantize_experts
            ):
                out[name] = quantize_tensor(v)
            elif name == "embed" and quantize_embed:
                out[name] = quantize_tensor(v)
            else:
                out[name] = v
        return out

    return convert(params)


# -- paged-KV quantization (host-DRAM tier + transfer wire) -----------------
#
# Symmetric per-page-per-head int8 for KV page slices of shape
# ``[n_layers, page_size, n_kv_heads, head_dim]``. One scale per
# (layer, kv_head) per page — coarse enough that scales are noise on the
# wire (n_layers * n_kv_heads f32 vs page_size * head_dim int8 payload),
# fine enough that an outlier head cannot poison the whole page's
# resolution. Deliberately numpy, not jax: both call sites (host-tier
# spill/restore and the transfer wire) already live on the host side of
# the batched-mover fence, so quantizing there adds zero device work and
# the Pallas paged-attention path never sees an int8 page.

#: modes accepted by the ``KV_QUANT`` knob
KV_QUANT_MODES = ("int8",)

#: modes accepted by the ``KV_QUANT_HBM`` knob (ISSUE 16). ``float8_e4m3``
#: is the declared follow-on storage mode: recognized here so the knob
#: surface is stable, but rejected with NotImplementedError at engine
#: init until the kernel grows an fp8 dequant path.
KV_QUANT_HBM_MODES = ("int8", "float8_e4m3")


def kv_scale_shape(page_shape: tuple[int, ...]) -> tuple[int, ...]:
    """Scale array shape for one quantized KV page slice."""
    n_layers, _, n_kv_heads, _ = page_shape
    return (n_layers, 1, n_kv_heads, 1)


def kv_hbm_scale_shape(pool_shape: tuple[int, ...]) -> tuple[int, ...]:
    """Scale pool shape for an int8 HBM KV pool
    ``[n_layers, total_pages, page_size, n_kv_heads, head_dim]`` →
    ``[n_layers, total_pages, n_kv_heads]``. One f32 scale per page per
    (layer, kv_head) — the SAME granularity as the host tier's
    :func:`kv_scale_shape`, so a page's codes and scales copy between
    tiers (and onto the PR 6 wire triple) with a reshape, never a
    dequant→requant round trip."""
    n_layers, total_pages, _, n_kv_heads, _ = pool_shape
    return (n_layers, total_pages, n_kv_heads)


def dequantize_kv_pool(
    q: np.ndarray, scales: np.ndarray, dtype: Any
) -> np.ndarray:
    """Full-width view of an int8 HBM pool ``[..., P, ps, n_kv, hd]`` with
    per-page scales ``[..., P, n_kv]`` — the tests' / oracle's view; the
    serving path never materializes this (the kernel dequantizes
    in-register)."""
    q32 = np.asarray(q, np.float32)
    s = np.asarray(scales, np.float32)[..., None, :, None]
    return (q32 * s).astype(dtype)


def quantize_kv_page(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize one KV page slice ``[n_layers, page_size, n_kv_heads, hd]``
    to int8 with per-(layer, kv_head) symmetric f32 scales. Error per
    element is bounded by ``scale / 2``; zeros round-trip exactly."""
    x32 = np.asarray(x, np.float32)
    amax = np.max(np.abs(x32), axis=(1, 3), keepdims=True)
    scale = np.maximum(amax, 1e-8).astype(np.float32) / 127.0
    q = np.clip(np.rint(x32 / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_kv_page(
    q: np.ndarray, scale: np.ndarray, dtype: Any
) -> np.ndarray:
    """Inverse of :func:`quantize_kv_page` into ``dtype`` (the engine's KV
    pool dtype — pages re-enter the paged-attention path full-width)."""
    return (q.astype(np.float32) * np.asarray(scale, np.float32)).astype(dtype)


def is_quantized(params: Any) -> bool:
    return any(
        isinstance(leaf, QuantizedTensor)
        for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        )
    )


def param_bytes(params: Any) -> int:
    """Total bytes of a param tree (counts int8 weights at 1 byte)."""
    return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(params))
