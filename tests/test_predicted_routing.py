"""Predicted-TTFT routing suite (ISSUE 14 acceptance).

The router's third generation: score-max (seed) → blended warmth/
affinity/load (round 4) → predicted-TTFT minimization (this round).
Coverage:

- **Predictor math**: the queue / miss-prefill / pull terms of the
  latency model, the prompt-work EMA, and the eligibility gates.
- **Corrector convergence**: an injected rate lie (heartbeats claiming a
  pod is fast when it is not) is corrected by the per-pod EWMA within a
  few audit joins, and the audit plane actually feeds it
  (``RouteAuditor(ttft_corrector=...)`` — the actuator loop).
- **Stale-heartbeat degradation** (satellite): a pod whose signals are
  older than 2x the heartbeat cadence decays to conservative defaults —
  a frozen shallow queue never attracts the fleet.
- **Never-pick gates**: draining / dead / kvstore / admission-closed
  pods predict ``inf``; with no eligible pod the router falls back to
  the legacy ranking (no failure mode worse than today).
- **Knobs-off parity** (the hard contract): ``BlendedRouter`` without a
  predictor — and WITH one that abstains — decides bit-identically to
  legacy; the scoring service with ``ROUTE_PREDICT`` unset reads no new
  body fields and keeps its legacy response//stats keys.
- **2-pod fleet acceptance**: real engines — the loaded warm pod loses
  the route to the idle colder pod, and the colder pod's measured TTFT
  wins.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from conftest import CharTokenizer
from llm_d_kv_cache_manager_tpu.kvcache import (
    BlendedRouter,
    KVCacheIndexer,
    KVCacheIndexerConfig,
    PodSignals,
    PredictionCorrector,
    PrefixAffinityTracker,
    TTFTPredictor,
    TTFTPredictorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.keys import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvevents import (
    FleetHealth,
    FleetHealthConfig,
)
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.obs.audit import RouteAuditor
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SamplingParams,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import PodServer, PodServerConfig

PS = 4
MODEL = "tiny-llama"
RATE = 100.0  # tokens/s — makes the expected seconds easy to read


def _pred(**kw):
    kw.setdefault("block_size", PS)
    # Unit tests pin raw model arithmetic; the tie band is exercised
    # explicitly where it matters.
    kw.setdefault("tie_band", 0.0)
    kw.setdefault("tie_abs_s", 0.0)
    return TTFTPredictor(TTFTPredictorConfig(**kw))


def _sig(name, q=0.0, rate=RATE, **kw):
    return PodSignals(name=name, queue_depth=q, prefill_rate=rate, **kw)


# ---------------------------------------------------------------------------
# Predictor math
# ---------------------------------------------------------------------------


class TestPredictorMath:
    def test_queue_term_scales_with_depth(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("a", q=4), _sig("b", q=0)], 100, scores={}
        )
        # Work EMA seeds at the prompt (100 tokens → 1.0 s service).
        # a: 4 queued x 1.0 + cold 100/100; b: cold only.
        assert arms["b"].ttft_s == pytest.approx(1.0)
        assert arms["a"].ttft_s == pytest.approx(5.0)

    def test_concurrency_divides_the_queue_wait(self):
        p = _pred(default_concurrency=4.0)
        arms = p.predict_routes(
            [_sig("a", q=4), _sig("b", q=0)], 100, scores={}
        )
        # 4 queued / width 4 = one service slot of wait, not four.
        assert arms["a"].ttft_s == pytest.approx(2.0)

    def test_miss_term_counts_the_unwarm_suffix(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("a"), _sig("b")], 100, scores={"a": 10}
        )
        # a holds 10 blocks x 4 = 40 warm tokens → 60 to prefill.
        assert arms["a"].ttft_s == pytest.approx(0.6)
        assert arms["b"].ttft_s == pytest.approx(1.0)
        assert arms["a"].action == "route_warm"

    def test_warm_reuse_caps_at_prompt_minus_one(self):
        p = _pred()
        arms = p.predict_routes([_sig("a")], 100, scores={"a": 1000})
        # The engine always computes one fresh position.
        assert arms["a"].ttft_s == pytest.approx(1.0 / RATE)

    def test_pull_arm_prices_the_wire_and_names_the_source(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("a", q=10), _sig("b", q=0)],
            100,
            scores={"a": 10},
            transfer_rate=1e6,
            block_bytes=1000,
        )
        # b pulls a's 10 warm blocks: 10 KB over 1 MB/s = 0.01 s wire +
        # 0.6 s suffix — beats b's 1.0 s cold arm.
        assert arms["b"].action == "pull"
        assert arms["b"].pull_source == "a"
        assert arms["b"].pull_blocks == 10
        assert arms["b"].ttft_s == pytest.approx(0.61)

    def test_pull_arm_needs_a_measured_link(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("a", q=10), _sig("b", q=0)], 100, scores={"a": 10}
        )
        # No transfer rate → the move can't be priced → no pull arm.
        assert arms["b"].action == "route_warm"

    def test_remote_holder_can_be_the_pull_source(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("a", q=0)],
            100,
            scores={"a": 2},
            remote_scores={"kvstore-0": 20},
            remote_endpoint_of=lambda h: f"tcp://{h}",
            transfer_rate=1e6,
            block_bytes=1000,
        )
        assert arms["a"].action == "pull"
        assert arms["a"].pull_source == "tcp://kvstore-0"

    def test_abstains_until_any_rate_is_measured(self):
        p = _pred()
        assert (
            p.predict_routes(
                [_sig("a", rate=None), _sig("b", rate=None)], 100, {}
            )
            is None
        )
        assert p.snapshot()["abstained"] == 1
        # No usable pod abstains AND counts (the /stats counter must
        # surface every "legacy routing handled this" condition).
        assert p.predict_routes([_sig("a", dead=True)], 100, {}) is None
        assert p.snapshot()["abstained"] == 2

    def test_negative_rate_is_unknown_not_a_negative_ttft(self):
        p = _pred()
        arms = p.predict_routes(
            [_sig("bad", q=0, rate=-100.0), _sig("ok", q=2, rate=RATE)],
            100,
            {},
        )
        # The corrupt rate decays to the fallback: a negative modeled
        # TTFT would win every route forever.
        assert arms["bad"].ttft_s > 0
        assert arms["bad"].ttft_s == pytest.approx(1.0)  # q=0, cold
        # A negative QUEUE is corrupt too — clamping it to "idle" would
        # convoy the fleet onto the broken pod; it decays to the
        # conservative fallback (deepest fresh queue + 1).
        arms2 = p.predict_routes(
            [_sig("neg", q=-5.0), _sig("ok", q=2, rate=RATE)], 100, {}
        )
        assert arms2["neg"].ttft_s > arms2["ok"].ttft_s
        # Negative rates alone can never arm the model.
        assert (
            p.predict_routes([_sig("x", rate=-5.0)], 100, {}) is None
        )

    def test_work_ema_tracks_prompt_lengths(self):
        p = _pred(work_ema_alpha=0.5)
        p.predict_routes([_sig("a")], 100, {})
        p.predict_routes([_sig("a")], 200, {})
        assert p.snapshot()["req_tokens_ema"] == pytest.approx(150.0)


# ---------------------------------------------------------------------------
# Corrector convergence (the injected rate lie)
# ---------------------------------------------------------------------------


class TestCorrector:
    def test_converges_to_the_lie_ratio(self):
        c = PredictionCorrector(alpha=0.5)
        # Closed loop: the raw model says 1.0 s, reality says 2.0 s, and
        # every new prediction applies the current bias. The fixed point
        # is bias == the lie ratio.
        for _ in range(40):
            c.observe("liar", 1.0 * c.bias("liar"), 2.0)
        assert c.bias("liar") == pytest.approx(2.0, rel=0.05)

    def test_clamp_bounds_one_absurd_sample(self):
        c = PredictionCorrector(alpha=1.0, lo=0.25, hi=4.0)
        c.observe("a", 0.001, 100.0)
        assert c.bias("a") == 4.0
        c2 = PredictionCorrector(alpha=1.0, lo=0.25, hi=4.0)
        c2.observe("a", 100.0, 0.001)
        assert c2.bias("a") == 0.25
        # A non-positive outcome is unusable, never a divide/flip.
        c3 = PredictionCorrector(alpha=1.0)
        assert c3.observe("a", 1.0, 0.0) is None
        assert c3.bias("a") == 1.0

    def test_unseen_pod_inherits_the_global_calibration_only(self):
        c = PredictionCorrector(alpha=1.0)
        c.observe("a", 1.0, 3.0)
        # The GLOBAL factor (geometric, global_alpha = alpha/2) carries
        # the fleet-systematic part to unseen pods; the per-pod residual
        # (the lie detector) stays theirs alone.
        assert c.bias("never-seen") == pytest.approx(3.0**0.5, rel=1e-3)
        assert c.bias("a") > c.bias("never-seen")

    def test_bias_scales_predictions(self):
        p = _pred()
        base = p.predict_routes([_sig("a")], 100, {})["a"].ttft_s
        for _ in range(50):
            p.corrector.observe("a", 1.0, 2.0)
        scaled = p.predict_routes([_sig("a")], 100, {})["a"].ttft_s
        assert scaled == pytest.approx(base * p.corrector.bias("a"))

    def test_audit_join_feeds_the_corrector(self):
        c = PredictionCorrector(alpha=1.0)
        a = RouteAuditor(ttft_corrector=c)
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=2,
            scoreboard={"pa": 2}, predicted_ttft_s=1.0,
        )
        rec = a.record_realized("r1", "pa", 2, realized_ttft_s=2.5)
        assert rec.ttft_ratio == pytest.approx(2.5)
        assert rec.predicted_ttft_s == 1.0 and rec.realized_ttft_s == 2.5
        assert c.observed == 1 and c.bias("pa") > 1.0
        assert a.snapshot()["ttft_ratio_p50"] == pytest.approx(2.5)
        # The row surfaces the TTFT columns on /debug/audit.
        (row,) = a.recent(request_id="r1")
        assert row["ttft_ratio"] == pytest.approx(2.5)

    def test_reroute_outcome_does_not_bias_the_chosen_pod(self):
        c = PredictionCorrector(alpha=1.0)
        a = RouteAuditor(ttft_corrector=c)
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=2,
            scoreboard={"pa": 2}, predicted_ttft_s=1.0,
        )
        # The request landed on pb: pb's latency is not pa's model error.
        rec = a.record_realized("r1", "pb", 0, realized_ttft_s=9.0)
        assert c.observed == 0
        # ...and the honesty ratio is not polluted either: the ratio's
        # denominator is pa's prediction, which was never followed.
        assert rec.ttft_ratio is None
        assert "ttft_ratio_p50" not in a.snapshot()

    def test_legacy_join_keeps_legacy_row_keys(self):
        a = RouteAuditor()
        a.record_decision(
            "r1", chosen_pod="pa", predicted_blocks=1, scoreboard={"pa": 1}
        )
        rec = a.record_realized("r1", "pa", 1)
        assert rec.ttft_ratio is None
        (row,) = a.recent(request_id="r1")
        assert "ttft_ratio" not in row and "predicted_ttft_s" not in row
        assert "ttft_ratio_p50" not in a.snapshot()

    def test_router_corrects_an_injected_rate_lie(self):
        """End-to-end convergence: a pod whose heartbeat claims 2x its
        real prefill rate keeps winning until the audit joins teach its
        residual, then routing fails over to the honest pod."""
        p = _pred(tie_band=0.0, tie_abs_s=0.0)
        auditor = RouteAuditor(ttft_corrector=p.corrector)
        sigs = {
            # Equal queues; "liar" claims double the real rate.
            "liar": _sig("liar", q=2, rate=2 * RATE),
            "honest": _sig("honest", q=2, rate=RATE),
        }
        router = BlendedRouter(
            score_fn=lambda toks, names: {},
            affinity=PrefixAffinityTracker(
                2, 64,
                token_processor=ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=PS)
                ),
            ),
            loads_fn=lambda names: [2.0, 2.0],
            auditor=auditor,
            predictor=p,
            signals_fn=lambda names: [sigs[n] for n in names],
        )
        pods = ["liar", "honest"]
        toks = list(range(40))
        # Both pods' TRUE latency at the honest rate: 2 queued + the
        # prompt = 120 tokens at 100 tok/s; the liar's claim halves it.
        truth = 1.2
        first = router.route(toks, pods, request_id="lie-0")
        assert first.pod == "liar"  # the lie wins at face value
        auditor.record_realized("lie-0", "liar", 0, realized_ttft_s=1.5)
        failed_over = False
        for i in range(1, 40):
            rid = f"lie-{i}"
            decision = router.route(toks, pods, request_id=rid)
            if decision.pod == "honest":
                failed_over = True
                break
            # The liar's joins keep exposing the lie...
            auditor.record_realized(rid, "liar", 0, realized_ttft_s=1.5)
            # ...while background traffic on the honest pod confirms
            # the model there (realized == its true latency), keeping
            # its residual honest as the global factor drifts.
            hrid = f"bg-{i}"
            auditor.record_decision(
                hrid, chosen_pod="honest", predicted_blocks=0,
                scoreboard={},
                predicted_ttft_s=truth * p.corrector.bias("honest"),
            )
            auditor.record_realized(hrid, "honest", 0, realized_ttft_s=truth)
        assert failed_over
        assert p.corrector.bias("liar") > p.corrector.bias("honest")


# ---------------------------------------------------------------------------
# Stale-heartbeat degradation (satellite)
# ---------------------------------------------------------------------------


class TestStaleHeartbeat:
    def test_stale_signals_decay_to_conservative_defaults(self):
        p = _pred(heartbeat_interval_s=1.0)
        arms = p.predict_routes(
            [
                # Frozen heartbeat: shallow queue + fast rate, 2.5 s old.
                _sig("stale", q=0, rate=10 * RATE, signal_age_s=2.5),
                _sig("fresh", q=8, rate=RATE, signal_age_s=0.2),
            ],
            100,
            {},
        )
        # The stale pod decays to the deepest fresh queue PLUS ONE and
        # the slowest fresh rate — unknown reads strictly worse than the
        # worst pod we have live signals for, so a frozen shallow queue
        # can never even tie its way back into winning.
        assert arms["stale"].ttft_s > arms["fresh"].ttft_s

    def test_age_within_two_beats_is_trusted(self):
        p = _pred(heartbeat_interval_s=1.0)
        arms = p.predict_routes(
            [
                _sig("young", q=0, rate=10 * RATE, signal_age_s=1.9),
                _sig("fresh", q=8, rate=RATE, signal_age_s=0.2),
            ],
            100,
            {},
        )
        assert arms["young"].ttft_s < arms["fresh"].ttft_s

    def test_every_signal_stale_abstains(self):
        p = _pred(heartbeat_interval_s=1.0)
        assert (
            p.predict_routes(
                [_sig("a", signal_age_s=5.0), _sig("b", signal_age_s=9.0)],
                100,
                {},
            )
            is None
        )

    def test_frozen_heartbeat_regression_with_fleet_health(self):
        """The satellite's regression: pod-a's heartbeat freezes while
        advertising an empty queue; the router must stop chasing it."""
        now = [1.0]
        fh = FleetHealth(FleetHealthConfig(), clock=lambda: now[0])
        fh.observe_heartbeat("pod-a", 0)
        telemetry = {
            "pod-a": (0.0, 10 * RATE),  # frozen claim: idle and fast
            "pod-b": (3.0, RATE),
        }

        def signals(names):
            views = fh.signal_views()
            return [
                PodSignals(
                    name=n,
                    queue_depth=telemetry[n][0],
                    prefill_rate=telemetry[n][1],
                    draining=views.get(n, {}).get("draining", False),
                    dead=views.get(n, {}).get("expired", False),
                    signal_age_s=views.get(n, {}).get("age_s"),
                )
                for n in names
            ]

        def router(hb):
            return BlendedRouter(
                score_fn=lambda toks, names: {},
                affinity=PrefixAffinityTracker(
                    2, 64,
                    token_processor=ChunkedTokenDatabase(
                        TokenProcessorConfig(block_size=PS)
                    ),
                ),
                loads_fn=lambda names: [telemetry[n][0] for n in names],
                predictor=_pred(heartbeat_interval_s=hb),
                signals_fn=signals,
            )

        pods = ["pod-a", "pod-b"]
        toks = list(range(40))
        # pod-a heartbeats stop; pod-b keeps beating for 5 intervals.
        for _ in range(5):
            now[0] += 1.0
            fh.observe_heartbeat("pod-b", 0)
        # Without the staleness gate the frozen "idle + fast" claim wins.
        assert router(hb=0.0).route(toks, pods).pod == "pod-a"
        # With it, pod-a's signals are unknown → conservative defaults
        # (pod-b's queue + rate), the tie resolves by live load → pod-b.
        assert router(hb=1.0).route(toks, pods).pod == "pod-b"


# ---------------------------------------------------------------------------
# Never-pick gates + legacy fallback
# ---------------------------------------------------------------------------


class TestNeverPick:
    def _router(self, sigs, loads=None, score_fn=None, predictor=None):
        names = [s.name for s in sigs]
        loads = loads or {n: 0.0 for n in names}
        return BlendedRouter(
            score_fn=score_fn or (lambda toks, p: {}),
            affinity=PrefixAffinityTracker(
                len(names), 64,
                token_processor=ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=PS)
                ),
            ),
            loads_fn=lambda p: [loads[n] for n in p],
            predictor=predictor or _pred(),
            signals_fn=lambda p: list(sigs),
        )

    @pytest.mark.parametrize(
        "bad",
        [
            dict(dead=True),
            dict(draining=True),
            dict(role="kvstore"),
            dict(admitting=False),
        ],
    )
    def test_ineligible_pod_never_picked_even_when_warm(self, bad):
        sigs = [_sig("bad", q=0, **bad), _sig("ok", q=50)]
        router = self._router(
            sigs, score_fn=lambda toks, p: {"bad": 100}
        )
        # All the warmth and an empty queue live on the ineligible pod;
        # the eligible one is deeply queued — and still wins.
        assert router.route(list(range(40)), ["bad", "ok"]).pod == "ok"

    def test_no_eligible_pod_falls_back_to_legacy_ranking(self):
        sigs = [_sig("a", dead=True), _sig("b", dead=True)]
        router = self._router(sigs, loads={"a": 5.0, "b": 1.0})
        # Prediction has no candidate; the legacy load ranking still
        # serves the request (no failure mode worse than today).
        decision = router.route(list(range(40)), ["a", "b"])
        assert decision.pod == "b"
        assert decision.predicted_ttft_s is None

    def test_tie_band_keeps_warmth_on_noise_deltas(self):
        p = _pred(tie_band=0.5, tie_abs_s=0.0)
        sigs = [_sig("warm", q=1), _sig("cold", q=0)]
        router = self._router(
            sigs, score_fn=lambda toks, pods_: {"warm": 9}, predictor=p
        )
        # cold predicts slightly better, but within the band the legacy
        # ranking (warmth first) holds the group together.
        decision = router.route(list(range(40)), ["warm", "cold"])
        assert decision.pod == "warm"


# ---------------------------------------------------------------------------
# Knobs-off parity
# ---------------------------------------------------------------------------


class TestKnobsOffParity:
    def _pair(self, with_predictor):
        ix = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            )
        )
        loads = {"a": 1.0, "b": 0.0, "c": 2.0}
        kw = {}
        if with_predictor:
            # A predictor whose signals never carry a measured rate
            # ABSTAINS on every decision — the contract is bit-identical
            # legacy routing.
            kw = dict(
                predictor=_pred(),
                signals_fn=lambda names: [
                    PodSignals(name=n, queue_depth=loads[n]) for n in names
                ],
            )
        router = BlendedRouter(
            score_fn=lambda toks, p: ix.score_tokens(toks, MODEL, p),
            affinity=PrefixAffinityTracker(
                3, 64,
                token_processor=ChunkedTokenDatabase(
                    TokenProcessorConfig(block_size=PS)
                ),
            ),
            loads_fn=lambda p: [loads[x] for x in p],
            **kw,
        )
        return ix, router

    def test_abstaining_predictor_is_bit_identical_legacy(self):
        ix1, legacy = self._pair(with_predictor=False)
        ix2, predict = self._pair(with_predictor=True)
        pods = ["a", "b", "c"]
        keys = ix1.token_processor.tokens_to_kv_block_keys(
            list(range(16)), MODEL
        )
        for ix in (ix1, ix2):
            ix.kv_block_index.add(keys, [PodEntry("c", "tpu_hbm")])
        try:
            for toks in (
                list(range(16)), list(range(16)), list(range(80, 96)),
                list(range(200, 232)),
            ):
                d1 = legacy.route(toks, pods)
                d2 = predict.route(toks, pods)
                assert (d1.pod, d1.action, d1.index_score, d1.affinity_score) == (
                    d2.pod, d2.action, d2.index_score, d2.affinity_score
                )
                assert d2.predicted_ttft_s is None
        finally:
            ix1.shutdown()
            ix2.shutdown()

    def test_scoring_service_knob_off_ignores_signals(self):
        from llm_d_kv_cache_manager_tpu.server.api import (
            ScoringService,
            ServiceConfig,
        )

        svc = ScoringService(
            ServiceConfig(native_index=False, enable_metrics=False),
            tokenizer=CharTokenizer(),
        )
        assert svc.predictor is None
        svc.indexer.get_pod_scores = (
            lambda prompt, model, pods, placement=None: {"pa": 1}
        )

        async def runner():
            ts = TestServer(svc.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.post(
                    "/score_completions",
                    json={
                        "prompt": "x" * 16,
                        "model": MODEL,
                        "signals": [
                            {"pod": "pa", "queue_depth": 1,
                             "prefill_rate": 100},
                        ],
                    },
                )
                data = await resp.json()
                assert set(data) == {"scores"}
                stats = await (await client.get("/stats")).json()
                assert "predict" not in stats
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            svc.indexer.shutdown()

    def test_scoring_service_route_predict_serves_predicted_ttft(self):
        from llm_d_kv_cache_manager_tpu.server.api import (
            ScoringService,
            ServiceConfig,
        )

        svc = ScoringService(
            ServiceConfig(
                native_index=False, enable_metrics=False,
                route_predict=True, block_size=PS,
            ),
            tokenizer=CharTokenizer(),
        )
        assert svc.predictor is not None
        svc.indexer.score_tokens = (
            lambda toks, model, pods, placement=None: {"pa": 2, "pb": 0}
        )
        # The predict path tokenizes (prompt length feeds the miss
        # term): the pool's workers must be live, as start() makes them.
        svc.indexer.run()

        async def runner():
            ts = TestServer(svc.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.post(
                    "/score_completions",
                    json={
                        "prompt": "x" * 16,
                        "model": MODEL,
                        "signals": [
                            {"pod": "pa", "queue_depth": 0,
                             "prefill_rate": 100},
                            {"pod": "pb", "queue_depth": 8,
                             "prefill_rate": 100},
                        ],
                    },
                )
                data = await resp.json()
                assert set(data) == {"scores", "predicted_ttft_s"}
                pred = data["predicted_ttft_s"]
                # Warm + idle beats cold + queued.
                assert pred["pa"] < pred["pb"]
                # A signals row naming a pod outside pod_identifiers is
                # dropped: predicted_ttft_s must never steer the caller
                # toward a pod the scoreboard's filters rejected.
                resp = await client.post(
                    "/score_completions",
                    json={
                        "prompt": "x" * 16,
                        "model": MODEL,
                        "pod_identifiers": ["pa"],
                        "signals": [
                            {"pod": "pa", "queue_depth": 0,
                             "prefill_rate": 100},
                            {"pod": "rogue", "queue_depth": 0,
                             "prefill_rate": 100},
                        ],
                    },
                )
                data = await resp.json()
                assert set(data["predicted_ttft_s"]) == {"pa"}
                # Without signals the response keeps its legacy keys
                # even with the knob on.
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": "x" * 16, "model": MODEL},
                )
                assert set(await resp.json()) == {"scores"}
                stats = await (await client.get("/stats")).json()
                assert "predict" in stats
                assert stats["predict"]["predictions"] >= 1
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            svc.indexer.shutdown()


# ---------------------------------------------------------------------------
# 2-pod fleet acceptance: the loaded warm pod loses, and rightly so
# ---------------------------------------------------------------------------


class TestFleetAcceptance:
    def _pod_config(self, pod_id):
        return PodServerConfig(
            model_name=MODEL,
            pod_identifier=pod_id,
            publish_events=False,
            engine=EngineConfig(
                model=TINY_LLAMA,
                block_manager=BlockManagerConfig(
                    total_pages=128, page_size=PS
                ),
                scheduler=SchedulerConfig(max_prefill_batch=2),
                max_model_len=96,
                decode_batch_size=2,
                prefill_bucket=8,
                interpret=True,
            ),
        )

    def test_loaded_warm_pod_loses_to_idle_cold_pod_and_ttft_agrees(self):
        indexer = KVCacheIndexer(
            KVCacheIndexerConfig(
                token_processor=TokenProcessorConfig(block_size=PS)
            )
        )
        pods = {
            "pod-a": PodServer(self._pod_config("pod-a")),
            "pod-b": PodServer(self._pod_config("pod-b")),
        }
        for p in pods.values():
            p.start()
        prefix = [(37 * i + 11) % 256 for i in range(32)]
        try:
            # Warm pod-a's prefix cache and its prefill-rate EMA.
            pods["pod-a"].generate(
                prefix + [1, 2, 3, 4], SamplingParams(max_new_tokens=2),
                timeout=120,
            )
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                prefix, MODEL
            )
            indexer.kv_block_index.add(keys, [PodEntry("pod-a", "tpu_hbm")])
            # Load pod-a with a backlog (past its 2-wide batch).
            backlog = [
                pods["pod-a"].submit(
                    [(53 * (i + 7) + j) % 256 for j in range(36)],
                    SamplingParams(max_new_tokens=12),
                )
                for i in range(8)
            ]
            # default_concurrency stays 1: PodServer.prefill_rate is the
            # engine's batch-aggregate EMA, already width-amortized.
            predictor = TTFTPredictor(TTFTPredictorConfig(block_size=PS))
            router = BlendedRouter(
                score_fn=lambda toks, names: indexer.score_tokens(
                    toks, MODEL, names
                ),
                affinity=PrefixAffinityTracker(
                    2, 64,
                    token_processor=ChunkedTokenDatabase(
                        TokenProcessorConfig(block_size=PS)
                    ),
                ),
                loads_fn=lambda names: [
                    pods[n].queue_depth for n in names
                ],
                predictor=predictor,
                signals_fn=lambda names: [
                    PodSignals(
                        name=n,
                        queue_depth=float(pods[n].queue_depth),
                        prefill_rate=pods[n].prefill_rate,
                    )
                    for n in names
                ],
            )
            prompt = prefix + [9, 8, 7, 6]
            decision = router.route(prompt, ["pod-a", "pod-b"])
            # Legacy score-max would queue behind the warmth; predicted
            # routing sends the request to the idle colder pod.
            assert decision.pod == "pod-b"
            assert decision.predicted_ttft_s is not None
            # Ground truth: identical probes on both pods — the idle
            # cold pod's measured TTFT beats the loaded warm pod's.
            fut_b = pods["pod-b"].submit(
                list(prompt), SamplingParams(max_new_tokens=2)
            )
            fut_a = pods["pod-a"].submit(
                list(prompt), SamplingParams(max_new_tokens=2)
            )
            seq_b = fut_b.result(timeout=300)
            seq_a = fut_a.result(timeout=300)
            assert seq_b.ttft is not None and seq_a.ttft is not None
            assert seq_b.ttft < seq_a.ttft
            for f in backlog:
                f.result(timeout=300)
        finally:
            for p in pods.values():
                p.shutdown()
            indexer.shutdown()
