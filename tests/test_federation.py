"""Fleet observability federation suite (ISSUE 20 acceptance).

- **FleetFederator unit**: registration, the dead-pod skip gate, error
  rows, the deterministic health-score formula, seq monotonicity, the
  bounded delta ring and its limit contract.
- **4-pod joined-vs-direct equality** (the acceptance pin): one
  ``/debug/fleet`` scrape over four HTTP-registered pods returns per-pod
  tier occupancy, SLO burn, and staleness that agree with each pod's own
  ``/stats`` surface fetched directly.
- **Trace exemplars** (``OBS_EXEMPLARS``): a forced-tail request's
  ``kvcache_request_ttft_seconds`` bucket carries an OpenMetrics
  exemplar whose trace_id resolves in ``/debug/traces``; knob off = no
  exemplar syntax anywhere in the exposition bytes and the classic
  content type.
- **Satellite 1**: the pod ``/stats`` scrape assembles every gated block
  from ONE locked cut (counting-lock pin + torn-read hammer on the
  fleet-migration counters).
- **Satellite 2**: every ``/debug/*`` GET on both APIs honors the
  Tracer limit contract (``limit<=0`` → nothing, junk → 400) and
  answers ``application/json``.
- **Satellite 3**: two-way exposition sweep — every family in the
  docs/observability.md catalog is actually emitted under its knob, and
  nothing emitted is undocumented.
- **kvtop**: renders against both an in-process federator and a scorer
  URL; disabled banner when the knob is off.
"""

import asyncio
import os
import re
import threading
import time

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from conftest import free_tcp_port
from llm_d_kv_cache_manager_tpu.kvcache.metrics import collector
from llm_d_kv_cache_manager_tpu.models import TINY_LLAMA
from llm_d_kv_cache_manager_tpu.obs.federation import (
    SCRAPE_SURFACES,
    FleetFederator,
    debug_fleet_payload,
)
from llm_d_kv_cache_manager_tpu.server import (
    BlockManagerConfig,
    EngineConfig,
    SchedulerConfig,
)
from llm_d_kv_cache_manager_tpu.server.api import (
    ScoringService,
    ServiceConfig,
)
from llm_d_kv_cache_manager_tpu.server.serve import (
    PodServer,
    PodServerConfig,
    _ServingMetrics,
)

PS = 4
MODEL = "tiny-llama"


def _engine_config(total_pages=64):
    return EngineConfig(
        model=TINY_LLAMA,
        block_manager=BlockManagerConfig(total_pages=total_pages, page_size=PS),
        scheduler=SchedulerConfig(max_prefill_batch=4),
        max_model_len=64,
        decode_batch_size=4,
        prefill_bucket=8,
        interpret=True,
    )


def _pod_config(pod_id, **kw):
    return PodServerConfig(
        model_name=MODEL,
        pod_identifier=pod_id,
        publish_events=False,
        engine=_engine_config(total_pages=kw.pop("total_pages", 64)),
        **kw,
    )


def _prompt(seed, n):
    return list(
        map(int, np.random.default_rng(seed).integers(0, TINY_LLAMA.vocab_size, n))
    )


def _stats(pod="p0", total=64, free=48, **extra):
    """A minimal legacy-shaped pod /stats payload for stub fetch hooks."""
    return {
        "pod": pod,
        "model": MODEL,
        "staged": 0,
        "waiting": 1,
        "running": 2,
        "free_pages": free,
        "total_pages": total,
        "prefill": {"requests": 3, "cached_prompt_tokens": 8},
        "transfer": {"breakers": {}},
        "drain": {"draining": False},
        **extra,
    }


def _stub_fetch(stats, **surfaces):
    """fetch hook serving /stats plus any explicit debug surfaces."""

    def fetch(path):
        if path == "/stats":
            return stats
        return surfaces.get(path.rsplit("/", 1)[-1])

    return fetch


class _StubHealth:
    """FleetHealth stand-in: scrape_views from a fixed expired set."""

    def __init__(self, expired=()):
        self.expired = set(expired)

    def scrape_views(self, pods):
        return {
            p: {
                "known": True,
                "expired": p in self.expired,
                "suspect": False,
                "draining": False,
                "age_s": 0.0,
            }
            for p in pods
        }


# ---------------------------------------------------------------------------
# FleetFederator unit
# ---------------------------------------------------------------------------


class TestFleetFederatorUnit:
    def test_registration_contract(self):
        fed = FleetFederator()
        with pytest.raises(ValueError):
            fed.register_pod("p0")
        fed.register_pod("p1", fetch=_stub_fetch(_stats("p1")))
        fed.register_pod("p0", url="http://localhost:1")
        assert fed.pods() == ["p0", "p1"]
        fed.drop_pod("p0")
        fed.drop_pod("p0")  # idempotent
        assert fed.pods() == ["p1"]

    def test_scrape_joins_tiers_queue_attribution(self):
        fed = FleetFederator()
        fed.register_pod(
            "p0",
            fetch=_stub_fetch(
                _stats("p0", total=64, free=48,
                       host={"cached": 5, "host_pages": 32})
            ),
        )
        snap = fed.scrape()
        row = snap["pods"]["p0"]
        assert row["ok"] is True
        assert row["tiers"]["tpu_hbm"] == {"used": 16, "total": 64, "fill": 0.25}
        assert row["tiers"]["host_dram"]["used"] == 5
        assert row["queue"] == {"staged": 0, "waiting": 1, "running": 2}
        assert row["attribution"]["cached_prompt_tokens"] == 8
        # Legacy pod (knobs off): no invented blocks.
        for absent in ("slo_burn", "quarantine", "mrc", "flight"):
            assert absent not in row
        assert snap["fleet"] == {
            "pods_ok": 1,
            "pods_failed": 0,
            "tiers": {
                "host_dram": {"used": 5, "total": 32, "fill": 0.1562},
                "tpu_hbm": {"used": 16, "total": 64, "fill": 0.25},
            },
            "health_score": 1.0,
        }

    def test_seq_monotone_and_ring_bounded(self):
        fed = FleetFederator(ring=3)
        fed.register_pod("p0", fetch=_stub_fetch(_stats()))
        seqs = [fed.scrape()["seq"] for _ in range(5)]
        assert seqs == [1, 2, 3, 4, 5]
        hist = fed.history(limit=50)
        assert [h["seq"] for h in hist] == [3, 4, 5]  # ring=3, oldest first
        assert fed.history(limit=1) == hist[-1:]
        assert fed.history(limit=0) == []
        assert fed.history(limit=-2) == []

    def test_expired_pod_skipped_without_fetching(self):
        calls = []

        def fetch(path):
            calls.append(path)
            return _stats("dead")

        fed = FleetFederator(health=_StubHealth(expired={"dead"}))
        fed.register_pod("dead", fetch=fetch)
        fed.register_pod("live", fetch=_stub_fetch(_stats("live")))
        snap = fed.scrape()
        assert calls == []  # the skip gate: zero fetches for the dead pod
        assert snap["pods"]["dead"] == {
            "ok": False,
            "skipped": "expired",
            "health": {
                "known": True, "expired": True, "suspect": False,
                "draining": False, "age_s": 0.0,
            },
        }
        assert snap["pods"]["live"]["ok"] is True
        assert fed.snapshot()["pods_skipped_dead"] == 1
        assert snap["fleet"] == {
            "pods_ok": 1,
            "pods_failed": 1,
            "tiers": {"tpu_hbm": {"used": 16, "total": 64, "fill": 0.25}},
            "health_score": 0.5,  # mean(1.0 live, 0.0 dead)
        }

    def test_stats_failure_is_an_error_row(self):
        def fetch(path):
            raise OSError("connection refused")

        fed = FleetFederator()
        fed.register_pod("down", fetch=fetch)
        snap = fed.scrape()
        row = snap["pods"]["down"]
        assert row["ok"] is False and "OSError" in row["error"]
        assert fed.snapshot()["scrape_errors"] == 1
        assert snap["fleet"]["health_score"] == 0.0

    def test_missing_debug_surface_is_not_an_error(self):
        def fetch(path):
            if path == "/stats":
                return _stats()
            raise OSError("404")  # pod predates the debug plane

        fed = FleetFederator()
        fed.register_pod("old", fetch=fetch)
        snap = fed.scrape()
        assert snap["pods"]["old"]["ok"] is True
        assert fed.snapshot()["scrape_errors"] == 0

    @pytest.mark.parametrize(
        "extra,expected",
        [
            ({}, 1.0),
            # any burn rate >= 1.0 costs 0.4
            ({"slo": {"burn_rates": {"ttft": {"60s": 2.0}}}}, 0.6),
            # burning below budget costs nothing
            ({"slo": {"burn_rates": {"ttft": {"60s": 0.5}}}}, 1.0),
            # any open breaker costs 0.2
            (
                {"transfer": {"breakers": {"tcp://x": {"state": "open"}}}},
                0.8,
            ),
            # quarantined copies cost 0.1
            ({"integrity": {"quarantined": 3}}, 0.9),
            # draining caps at 0.5 (even an otherwise-healthy pod)
            ({"drain": {"draining": True}}, 0.5),
        ],
    )
    def test_health_score_formula(self, extra, expected):
        stats = _stats()
        for key, val in extra.items():
            if key == "transfer":
                stats["transfer"] = val
            elif key == "drain":
                stats["drain"] = val
            else:
                stats[key] = val
        fed = FleetFederator()
        fed.register_pod("p0", fetch=_stub_fetch(stats))
        assert fed.scrape()["fleet"]["health_score"] == expected
        assert fed.health_score() == expected

    def test_health_score_hbm_pressure_and_clamp(self):
        # fill >= 0.95 costs 0.2; penalties stack and clamp at 0.
        stats = _stats(total=64, free=2)
        stats["slo"] = {"burn_rates": {"ttft": {"60s": 9.0}}}
        stats["transfer"] = {"breakers": {"a": {"state": "open"}}}
        stats["integrity"] = {"quarantined": 1}
        fed = FleetFederator()
        fed.register_pod("p0", fetch=_stub_fetch(stats))
        # 1.0 - 0.4 - 0.2 - 0.2 - 0.1 = 0.1
        assert fed.scrape()["fleet"]["health_score"] == 0.1

    def test_health_score_none_on_empty_fleet(self):
        fed = FleetFederator()
        assert fed.scrape()["fleet"]["health_score"] is None
        assert fed.health_score() is None

    def test_staleness_join_writes_events_behind(self):
        class StubStaleness:
            def snapshot(self):
                return {"events_behind": {"p0": 7, "ghost": 3}}

        fed = FleetFederator(staleness=StubStaleness())
        fed.register_pod("p0", fetch=_stub_fetch(_stats("p0")))
        snap = fed.scrape()
        assert snap["pods"]["p0"]["events_behind"] == 7
        assert snap["staleness"]["events_behind"]["ghost"] == 3

    def test_on_scrape_hook_fires_and_failures_are_swallowed(self):
        seen = []

        def hook(took, errors, skipped, health):
            seen.append((errors, skipped, health))
            raise RuntimeError("metrics mirror broke")

        fed = FleetFederator(on_scrape=hook)
        fed.register_pod("p0", fetch=_stub_fetch(_stats()))
        snap = fed.scrape()  # the hook raising must not break the scrape
        assert snap["pods"]["p0"]["ok"] is True
        assert seen == [(0, 0, 1.0)]

    def test_delta_row_shape(self):
        stats = _stats(total=64, free=0)
        stats["slo"] = {"burn_rates": {"ttft": {"60s": 1.5, "300s": 0.4}}}
        fed = FleetFederator()
        fed.register_pod("p0", fetch=_stub_fetch(stats))
        fed.scrape()
        (row,) = fed.history()
        assert row["pods"]["p0"] == {
            "ok": True, "hbm_fill": 1.0, "burn_max": 1.5, "draining": False,
        }
        assert row["health_score"] == 0.4  # burn (0.4) + hbm pressure (0.2)

    def test_debug_fleet_payload_contract(self):
        assert debug_fleet_payload(None, {}) == (
            200,
            {"enabled": False, "pods": {}, "history": []},
        )
        fed = FleetFederator()
        fed.register_pod("p0", fetch=_stub_fetch(_stats()))
        status, payload = debug_fleet_payload(fed, {"limit": "zzz"})
        assert status == 400 and "limit" in payload["error"]
        status, payload = debug_fleet_payload(fed, {"limit": "0"})
        assert status == 200 and payload["history"] == []
        assert payload["enabled"] is True and payload["pods"]["p0"]["ok"]
        # Each GET is a FRESH scrape, not a cached view.
        assert debug_fleet_payload(fed, {})[1]["seq"] == payload["seq"] + 1

    def test_scrape_surfaces_pinned(self):
        # kvtop, the docs, and the pods' route tables all assume this set.
        assert SCRAPE_SURFACES == (
            "/stats",
            "/debug/staleness",
            "/debug/mrc",
            "/debug/lifecycle",
            "/debug/audit",
        )


# ---------------------------------------------------------------------------
# Scorer HTTP surface (/debug/fleet, /stats fed block, knobs-off parity)
# ---------------------------------------------------------------------------


def _run_scorer(scenario, **cfg_kw):
    svc = ScoringService(
        ServiceConfig(native_index=False, enable_metrics=False, **cfg_kw)
    )

    async def runner():
        ts = TestServer(svc.build_app())
        client = TestClient(ts)
        await client.start_server()
        try:
            await scenario(client, svc)
        finally:
            await client.close()

    try:
        asyncio.run(runner())
    finally:
        svc.indexer.shutdown()


class TestScorerFederationEndpoint:
    def test_knob_off_is_disabled_shaped_and_stats_unchanged(self):
        async def scenario(c, svc):
            assert svc.federator is None
            resp = await c.get("/debug/fleet")
            assert resp.status == 200
            assert await resp.json() == {
                "enabled": False, "pods": {}, "history": [],
            }
            stats = await (await c.get("/stats")).json()
            assert "fed" not in stats
            # The knobs-off scorer /stats key set stays bit-identical.
            assert set(stats) == {
                "fleet", "subscriber", "events_rejected_after_shutdown",
                "index_size", "index",
            }

        _run_scorer(scenario)

    def test_knob_on_scrapes_and_stats_gains_fed_block(self):
        async def scenario(c, svc):
            assert svc.federator is not None
            svc.federator.register_pod("p0", fetch=_stub_fetch(_stats("p0")))
            resp = await c.get("/debug/fleet")
            assert resp.status == 200
            assert resp.content_type == "application/json"
            data = await resp.json()
            assert data["enabled"] is True
            assert data["pods"]["p0"]["ok"] is True
            assert data["fleet"]["health_score"] == 1.0
            assert len(data["history"]) == 1
            resp = await c.get("/debug/fleet?limit=bogus")
            assert resp.status == 400
            stats = await (await c.get("/stats")).json()
            assert stats["fed"]["pods_registered"] == 1
            # One scrape per successful GET (the bogus-limit GET failed
            # validation before scraping).
            assert stats["fed"]["scrapes"] == 1
            assert stats["fed"]["scrape_errors"] == 0

        _run_scorer(scenario, obs_fed=True)

    def test_from_env_reads_fed_knobs(self, monkeypatch):
        monkeypatch.setenv("OBS_FED", "1")
        monkeypatch.setenv("OBS_FED_RING", "7")
        monkeypatch.setenv("OBS_FED_TIMEOUT_S", "0.25")
        monkeypatch.setenv("OBS_EXEMPLARS", "1")
        cfg = ServiceConfig.from_env()
        assert cfg.obs_fed is True and cfg.obs_exemplars is True
        assert cfg.obs_fed_ring == 7 and cfg.obs_fed_timeout_s == 0.25
        for var in ("OBS_FED", "OBS_FED_RING", "OBS_FED_TIMEOUT_S",
                    "OBS_EXEMPLARS"):
            monkeypatch.delenv(var)
        cfg = ServiceConfig.from_env()
        assert cfg.obs_fed is False and cfg.obs_exemplars is False


# ---------------------------------------------------------------------------
# The acceptance pin: 4-pod fleet, joined vs direct
# ---------------------------------------------------------------------------


class TestFourPodJoinedVsDirect:
    def test_debug_fleet_agrees_with_each_pods_own_surfaces(self):
        # Pod 0 runs with an impossible TTFT objective so one completed
        # request forces an SLO burn >= 1 into its /stats slo block; the
        # other three are legacy-shaped (no obs knobs).
        pods = [
            PodServer(
                _pod_config(
                    "fed-p0", obs_slo="ttft:0.000001:0.99", obs_metrics=True
                )
            )
        ] + [PodServer(_pod_config(f"fed-p{i}")) for i in range(1, 4)]
        for p in pods:
            p.start()
        svc = ScoringService(
            ServiceConfig(
                native_index=False, enable_metrics=False,
                obs_fed=True, obs_audit=True,
            )
        )

        async def runner():
            loop = asyncio.get_running_loop()
            runners, direct = [], {}
            try:
                for i, pod in enumerate(pods):
                    runner_ = web.AppRunner(pod.build_app())
                    await runner_.setup()
                    runners.append(runner_)
                    port = free_tcp_port()
                    site = web.TCPSite(runner_, "127.0.0.1", port)
                    await site.start()
                    svc.federator.register_pod(
                        f"fed-p{i}", url=f"http://127.0.0.1:{port}"
                    )
                # One real completion on pod 0 → ttft burn + prefill stats.
                ts = TestServer(svc.build_app())
                client = TestClient(ts)
                await client.start_server()
                import aiohttp

                async with aiohttp.ClientSession() as sess:
                    url = f"http://127.0.0.1:{runners[0].addresses[0][1]}"
                    resp = await sess.post(
                        url + "/v1/completions",
                        json={
                            "prompt_token_ids": _prompt(0, 12),
                            "max_tokens": 3,
                        },
                    )
                    assert resp.status == 200
                try:
                    # The federated view, over real HTTP to real pods.
                    resp = await client.get("/debug/fleet")
                    assert resp.status == 200
                    snap = await resp.json()
                    # Direct per-pod surfaces for the equality check
                    # (urllib in the federator runs in an executor; here
                    # the fetches ride the test loop's own session).
                    async with aiohttp.ClientSession() as sess:
                        for i, runner_ in enumerate(runners):
                            port = runner_.addresses[0][1]
                            base = f"http://127.0.0.1:{port}"
                            direct[f"fed-p{i}"] = await (
                                await sess.get(base + "/stats")
                            ).json()
                finally:
                    await client.close()
            finally:
                for runner_ in runners:
                    await runner_.cleanup()
            return snap, direct

        try:
            snap, direct = asyncio.run(runner())
        finally:
            svc.indexer.shutdown()
            for p in pods:
                p.shutdown()

        assert snap["fleet"]["pods_ok"] == 4
        assert snap["fleet"]["pods_failed"] == 0
        for name, stats in direct.items():
            row = snap["pods"][name]
            assert row["ok"] is True, row
            # Tier occupancy agrees with the pod's own ledger.
            total, free = stats["total_pages"], stats["free_pages"]
            assert row["tiers"]["tpu_hbm"]["total"] == total
            assert row["tiers"]["tpu_hbm"]["used"] == total - free
            assert row["draining"] is stats["drain"]["draining"]
            # Hit/miss attribution mix == the pod's own prefill counters.
            assert row["attribution"] == stats["prefill"]
        # SLO burn: the joined row carries pod 0's own burn rates, and the
        # impossible objective burned >= 1 on at least one window.
        burn = snap["pods"]["fed-p0"]["slo_burn"]
        assert burn == direct["fed-p0"]["slo"]["burn_rates"]
        assert any(
            rate is not None and rate >= 1.0
            for windows in burn.values()
            for rate in windows.values()
        )
        for i in range(1, 4):  # legacy pods: no invented slo block
            assert "slo_burn" not in snap["pods"][f"fed-p{i}"]
        # Staleness: the joined top-level block is the scorer's own
        # tracker view (pods publish no events here, so behind is empty
        # on both sides — the agreement is the point).
        assert snap["staleness"]["events_behind"] == {}
        # The fleet tier rollup sums the per-pod ledgers.
        hbm = snap["fleet"]["tiers"]["tpu_hbm"]
        assert hbm["total"] == sum(s["total_pages"] for s in direct.values())
        assert hbm["used"] == sum(
            s["total_pages"] - s["free_pages"] for s in direct.values()
        )


# ---------------------------------------------------------------------------
# Trace exemplars (OBS_EXEMPLARS)
# ---------------------------------------------------------------------------


_EXEMPLAR_RE = re.compile(
    r'kvcache_request_ttft_seconds_bucket\{[^}]*\}\s+\S+\s+'
    r'#\s+\{trace_id="([0-9a-f]{32})"\}'
)


class TestExemplars:
    def test_tail_ttft_bucket_exemplar_resolves_in_debug_traces(self):
        server = PodServer(
            _pod_config(
                "exm-pod",
                obs_tracing=True,
                obs_metrics=True,
                obs_exemplars=True,
            )
        )
        server.start()

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.post(
                    "/v1/completions",
                    json={"prompt_token_ids": _prompt(3, 12), "max_tokens": 3},
                )
                assert resp.status == 200
                resp = await client.get("/metrics")
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "application/openmetrics-text"
                )
                text = (await resp.read()).decode()
                match = _EXEMPLAR_RE.search(text)
                assert match, "no exemplar on any ttft bucket"
                tid = match.group(1)
                resp = await client.get(f"/debug/traces?trace_id={tid}")
                data = await resp.json()
                assert data["enabled"] is True
                (trace,) = data["traces"]
                assert trace["trace_id"] == tid
                assert any(
                    s["name"] == "pod.request" for s in trace["spans"]
                )
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            server.shutdown()

    def test_knob_off_keeps_classic_exposition_bit_identical(self):
        server = PodServer(_pod_config("exm-off", obs_metrics=True))
        server.start()

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                resp = await client.post(
                    "/v1/completions",
                    json={"prompt_token_ids": _prompt(4, 12), "max_tokens": 3},
                )
                assert resp.status == 200
                resp = await client.get("/metrics")
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = (await resp.read()).decode()
                # No exemplar syntax anywhere in the classic bytes, and
                # the TTFT family is present to prove we looked at the
                # exposition that WOULD carry them.
                assert "kvcache_request_ttft_seconds_bucket" in body
                assert "trace_id=" not in body
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            server.shutdown()

    def test_serving_metrics_pull_exemplar_unit(self):
        pytest.importorskip("prometheus_client")
        from prometheus_client.openmetrics import exposition as om

        m = _ServingMetrics(obs=True, exemplars=True)
        m.observe_pull(0.02, "ok", trace_id="ab" * 16)
        text = om.generate_latest(m.registry).decode()
        assert 'trace_id="' + "ab" * 16 + '"' in text
        # Same observation without a trace id: plain bucket, no exemplar.
        m2 = _ServingMetrics(obs=True, exemplars=True)
        m2.observe_pull(0.02, "ok")
        assert "trace_id=" not in om.generate_latest(m2.registry).decode()

    def test_scorer_metrics_switch_content_type_under_knob(self):
        async def scenario(c, svc):
            resp = await c.get("/metrics")
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )

        _run_scorer(scenario, obs_exemplars=True)

        async def scenario_off(c, svc):
            resp = await c.get("/metrics")
            assert resp.headers["Content-Type"].startswith("text/plain")

        _run_scorer(scenario_off)

    def test_collector_score_latency_exemplar(self):
        prom = pytest.importorskip("prometheus_client")
        from prometheus_client.openmetrics import exposition as om

        collector.register()
        collector.observe_score_latency(0.004, trace_id="cd" * 16)
        text = om.generate_latest(prom.REGISTRY).decode()
        assert 'trace_id="' + "cd" * 16 + '"' in text


# ---------------------------------------------------------------------------
# Satellite 1: /stats is ONE locked cut
# ---------------------------------------------------------------------------


class _CountingLock:
    """Lock proxy counting per-thread acquisitions — the /stats one-cut
    pin. Per-thread so the pod's background loops (which also take _mu on
    their own threads) cannot pollute the handler-thread count."""

    def __init__(self, inner):
        self._inner = inner
        self.by_thread: dict = {}

    def _count(self):
        tid = threading.get_ident()
        self.by_thread[tid] = self.by_thread.get(tid, 0) + 1

    def __enter__(self):
        self._count()
        return self._inner.__enter__()

    def __exit__(self, *exc):
        return self._inner.__exit__(*exc)

    def acquire(self, *a, **kw):
        self._count()
        return self._inner.acquire(*a, **kw)

    def release(self):
        return self._inner.release()


class TestStatsSingleCut:
    def test_stats_acquires_the_server_lock_exactly_once(self):
        # fleet_controller on: the fleet block used to re-acquire _mu for
        # the migration counters — a second hold in one scrape could pair
        # fresh migration counts with stale queue state.
        server = PodServer(_pod_config("cut-pod", fleet_controller=True))
        server.start()
        proxy = _CountingLock(server._mu)
        server._mu = proxy

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                # The stats handler runs on this (the event loop's) thread.
                tid = threading.get_ident()
                before = proxy.by_thread.get(tid, 0)
                resp = await client.get("/stats")
                stats = await resp.json()
                handler_holds = proxy.by_thread.get(tid, 0) - before
                assert stats["fleet"]["migrations_out"] == 0
                # Exactly one locked cut per scrape.
                assert handler_holds == 1, handler_holds
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            server._mu = proxy._inner
            server.shutdown()

    def test_migration_counters_never_torn(self):
        # Writer bumps migrations_out and migrations_in TOGETHER under
        # _mu; any scrape observing them unequal read a torn cut.
        server = PodServer(_pod_config("torn-pod", fleet_controller=True))
        server.start()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                with server._mu:
                    server.migrations_out += 1
                    server.migrations_in += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()

        async def runner():
            ts = TestServer(server.build_app())
            client = TestClient(ts)
            await client.start_server()
            try:
                for _ in range(50):
                    stats = await (await client.get("/stats")).json()
                    fleet = stats["fleet"]
                    assert fleet["migrations_out"] == fleet["migrations_in"]
            finally:
                await client.close()

        try:
            asyncio.run(runner())
        finally:
            stop.set()
            t.join(timeout=5)
            server.shutdown()


# ---------------------------------------------------------------------------
# Satellite 2: debug-endpoint conformance on both APIs
# ---------------------------------------------------------------------------

#: route -> the payload field holding the capped rows (absent field is an
#: acceptable "nothing" — e.g. a flight recorder with no timeline yet).
_POD_DEBUG_ROUTES = {
    "/debug/traces": "traces",
    "/debug/lifecycle": "recent",
    "/debug/mrc": "curve",
    "/debug/flight": "timeline",
}
_SCORER_DEBUG_ROUTES = {
    "/debug/traces": "traces",
    "/debug/staleness": "per_pod_event",
    "/debug/audit": "audits",
    "/debug/lifecycle": "recent",
    "/debug/mrc": "curve",
    "/debug/fleet": "history",
}


async def _start_client(app):
    """TestClient must be built on a running loop (its CookieJar grabs
    the running loop at construction)."""
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def _assert_capped_empty(payload, field):
    if field == "timeline":
        timeline = payload.get("timeline")
        assert timeline is None or timeline.get("entries") in ([], None)
        return
    rows = payload.get(field)
    assert rows in ([], {}, None), (field, rows)


class TestDebugEndpointConformance:
    @pytest.fixture(scope="class")
    def pod_client(self):
        # All debug knobs on so every endpoint parses its limit (a
        # disabled endpoint short-circuits before the query).
        server = PodServer(
            _pod_config(
                "dbg-pod",
                obs_tracing=True,
                obs_lifecycle=True,
                obs_flight=True,
            )
        )
        server.start()
        loop = asyncio.new_event_loop()
        client = loop.run_until_complete(_start_client(server.build_app()))
        yield loop, client
        loop.run_until_complete(client.close())
        loop.close()
        server.shutdown()

    @pytest.fixture(scope="class")
    def scorer_client(self):
        svc = ScoringService(
            ServiceConfig(
                native_index=False, enable_metrics=False,
                obs_tracing=True, obs_audit=True, obs_lifecycle=True,
                obs_fed=True,
            )
        )
        loop = asyncio.new_event_loop()
        client = loop.run_until_complete(_start_client(svc.build_app()))
        yield loop, client
        loop.run_until_complete(client.close())
        loop.close()
        svc.indexer.shutdown()

    @pytest.mark.parametrize("route", sorted(_POD_DEBUG_ROUTES))
    def test_pod_debug_conformance(self, pod_client, route):
        loop, client = pod_client
        self._conformance(loop, client, route, _POD_DEBUG_ROUTES[route])

    @pytest.mark.parametrize("route", sorted(_SCORER_DEBUG_ROUTES))
    def test_scorer_debug_conformance(self, scorer_client, route):
        loop, client = scorer_client
        self._conformance(loop, client, route, _SCORER_DEBUG_ROUTES[route])

    @staticmethod
    def _conformance(loop, client, route, field):
        async def scenario():
            # Default query: 200, JSON.
            resp = await client.get(route)
            assert resp.status == 200
            assert resp.content_type == "application/json"
            # limit<=0 returns nothing (the Tracer contract).
            for limit in ("0", "-3"):
                resp = await client.get(f"{route}?limit={limit}")
                assert resp.status == 200, route
                _assert_capped_empty(await resp.json(), field)
            # Junk limit: tolerant 400, JSON error body, never a 500.
            resp = await client.get(f"{route}?limit=bogus")
            assert resp.status == 400, route
            assert resp.content_type == "application/json"
            assert "limit" in (await resp.json())["error"]

        loop.run_until_complete(scenario())


# ---------------------------------------------------------------------------
# Satellite 3: two-way exposition sweep vs the docs catalog
# ---------------------------------------------------------------------------


def _docs_catalog_names():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs",
        "observability.md",
    )
    names = set()
    with open(path) as fh:
        for line in fh:
            m = re.match(r"\|\s*`(kvcache_[a-z0-9_]+)`", line)
            if m:
                names.add(m.group(1))
    return names


def _exposition_types(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(" ")
            out[name] = typ
    return out


class TestExpositionSweep:
    def test_every_catalog_family_is_emitted_and_vice_versa(self):
        prom = pytest.importorskip("prometheus_client")
        # The pod surface with every registry-shaping knob on, plus the
        # scorer's global collector registry: between them, every
        # documented family must appear as a # TYPE line (registered
        # families expose TYPE even with zero samples).
        m = _ServingMetrics(
            obs=True, lifecycle=True, tenant_qos=True, integrity=True
        )
        collector.register()
        emitted = {
            name
            for name in {
                **_exposition_types(m.exposition().decode()),
                **_exposition_types(prom.generate_latest().decode()),
            }
            # _created series are prometheus_client bookkeeping, not
            # catalog families.
            if name.startswith("kvcache_") and not name.endswith("_created")
        }
        docs = _docs_catalog_names()
        assert docs, "catalog extraction found nothing — regex drift?"
        missing = docs - emitted
        assert not missing, f"documented but never emitted: {sorted(missing)}"
        undocumented = emitted - docs
        assert not undocumented, (
            f"emitted but not in docs/observability.md: {sorted(undocumented)}"
        )

    def test_federation_families_present_under_knob(self):
        prom = pytest.importorskip("prometheus_client")
        collector.register()
        collector.observe_fleet_scrape(0.01, errors=1, skipped=2, health=0.75)
        types = _exposition_types(prom.generate_latest().decode())
        assert types["kvcache_fleet_health_score"] == "gauge"
        assert types["kvcache_fleet_scrape_seconds"] == "histogram"
        assert types["kvcache_fleet_scrape_errors_total"] == "counter"
        assert types["kvcache_fleet_scrape_pods_skipped_total"] == "counter"


# ---------------------------------------------------------------------------
# kvtop
# ---------------------------------------------------------------------------


class TestKvtop:
    def _fed(self):
        fed = FleetFederator()
        burn_stats = _stats("pod-burn", total=64, free=2)
        burn_stats["slo"] = {"burn_rates": {"ttft": {"60s": 2.5}}}
        burn_stats["drain"] = {"draining": True}
        fed.register_pod("pod-burn", fetch=_stub_fetch(burn_stats))
        fed.register_pod("pod-ok", fetch=_stub_fetch(_stats("pod-ok")))

        def down(path):
            raise OSError("refused")

        fed.register_pod("pod-down", fetch=down)
        return fed

    def test_render_against_in_process_federator(self):
        from tools.kvtop import fetch_snapshot, render_plain

        fed = self._fed()
        fed.scrape()  # a prior scrape so history has a sparkline point
        frame = render_plain(fetch_snapshot(fed))
        assert "kvtop — fleet seq 2" in frame
        assert "pods 2 ok / 1 failed" in frame
        assert "DOWN (OSError: refused)" in frame
        assert "DRAINING" in frame and "BURN 2.5x" in frame
        assert "tpu_hbm" in frame and "health" in frame

    def test_render_disabled_payload(self):
        from tools.kvtop import render_plain

        frame = render_plain({"enabled": False})
        assert "federation disabled" in frame and "OBS_FED=1" in frame

    def test_fetch_against_scorer_url(self):
        from tools.kvtop import fetch_snapshot, render_plain

        svc = ScoringService(
            ServiceConfig(
                native_index=False, enable_metrics=False, obs_fed=True
            )
        )
        svc.federator.register_pod("p0", fetch=_stub_fetch(_stats("p0")))

        async def runner():
            loop = asyncio.get_running_loop()
            runner_ = web.AppRunner(svc.build_app())
            await runner_.setup()
            try:
                port = free_tcp_port()
                site = web.TCPSite(runner_, "127.0.0.1", port)
                await site.start()
                # urllib blocks — keep the serving loop free.
                return await loop.run_in_executor(
                    None,
                    fetch_snapshot,
                    f"http://127.0.0.1:{port}",
                )
            finally:
                await runner_.cleanup()

        try:
            payload = asyncio.run(runner())
        finally:
            svc.indexer.shutdown()
        assert payload["enabled"] is True
        frame = render_plain(payload)
        assert "p0" in frame and "pods 1 ok / 0 failed" in frame

    def test_cli_once_against_down_scorer_reports_error(self, capsys):
        from tools.kvtop.__main__ import main

        port = free_tcp_port()  # nothing listening
        rc = main([
            "--url", f"http://127.0.0.1:{port}", "--once", "--timeout", "0.2",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "kvtop: fetch failed" in out

    def test_sparkline_and_bar_primitives(self):
        from tools.kvtop import _bar, sparkline

        assert _bar(0.0) == "[----------]   0%"
        assert _bar(1.0) == "[##########] 100%"
        assert _bar(None).endswith("--")
        assert sparkline([0.0, 0.5, 1.0, None]) == "▁▅█ "
